"""The fused selector sweep: the WHOLE fold x grid model sweep as ONE launch.

Reference parity: OpValidator.scala:299-357 trains numFolds x models x grids
Spark fits on an 8-thread JVM pool and evaluates each on its own Spark job.
The TPU-first replacement batches everything:

- every family's fold x grid block is a vmapped training program (linear
  FISTA/Newton, histogram forests, scan-over-rounds boosting),
- bootstrap / feature-subset / row-subsample draws happen ON DEVICE
  (ops/trees.rng_keys scheme, shared with ``fit_arrays`` for parity),
- validation metrics (ops/metrics) are computed on device for all
  fold x candidate pairs at once,

and — the round-5 step — ALL of it runs inside ONE jitted program driven by
a hashable static ``spec``, so a steady-state sweep costs one host->device
upload (fold weights + hyperparameter blob), one launch, and one [F, C, M]
metrics pull.  On a tunneled TPU backend every launch/transfer pays tens of
milliseconds of wire latency (measured ~25-70 ms), which made the legacy
per-family path latency-bound at ~25 models/s; the fused program removes
~all of it.

Spec grammar (static, hashable; built by impl/sweep_fragments.py).  Every
fragment's ``cis`` is the tuple of candidate positions (static ints) it
fills in the GLOBAL candidate order; ``off_*`` index the dynamic f32
hyperparameter ``blob``; ``xb_idx`` picks the pre-binned matrix in ``xbs``:

    spec = (problem, frags, strict)
    problem ∈ {"binary", "regression", ("multiclass", k)}
    frag = ("fista",  cis, max_iter, fit_intercept, off_l1, off_l2)
         | ("newton", cis, max_iter, fit_intercept, off_l2)
         | ("svc",    cis, max_iter, fit_intercept, off_l2)
         | ("mlp",    cis, layers, max_iter, off_lr, off_seed)
         | ("forest", out_c, groups)   # RF / DT
         | ("gbt", loss, out_c, groups)
    forest group = (cis, depth, n_trees, xb_idx, n_bins, frac, rate,
                    bootstrap, seed, frontier, exact_cap, chunk,
                    off_mcw, off_mig)
    gbt group    = (cis, rounds, depth, xb_idx, n_bins, subsample, colsample,
                    seed, frontier, exact_cap, fold_base, trees_per_round,
                    off_eta, off_lam, off_gam, off_mcw, off_mig)

``trees_per_round`` (K) is the round-collapse factor: K > 1 shortens the
boosting scan to rounds / K steps, growing K trees per step at eta / K
(ops/trees._gbt_batch_impl).  K = 1 is the exact per-round scan.

``strict`` is the per-candidate 0/1 tuple choosing ``score > 0.5`` vs
``>= 0.5`` for the class decision (matches each family's host
``predict_arrays`` convention).  The interpreter returns the stacked
metrics tensor [F, C, M] (metric order: ops/metrics.BINARY_METRICS or
REGRESSION_METRICS).
"""
from __future__ import annotations

import functools
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35-ish exports shard_map at top level
    from jax import shard_map as _shard_map
    _no_check = {"check_vma": False}
except ImportError:  # the 0.4.x experimental home
    from jax.experimental.shard_map import shard_map as _shard_map
    _no_check = {"check_rep": False}

from ..obs import ledger as _ledger
from ..obs import registry as obs_registry
from ..obs import trace
from ..parallel import mesh as mesh_mod
from ..resilience import checkpoint as _ckpt
from ..resilience import hedge as _hedge
from ..resilience import health as _health
from ..resilience import inject as _inject
from ..resilience import retry as _retry
from ..parallel.mesh import mesh_all_gather, mesh_psum
from ..utils import devcache, flops
from . import linear as L
from . import trees as Tr
from .metrics import (BINARY_METRICS, MULTICLASS_METRICS, REGRESSION_METRICS,
                      _binary_grid_metrics, _binary_one,
                      _multiclass_grid_metrics, _multiclass_one,
                      _regression_grid_metrics, _regression_one)

__all__ = ["run_sweep", "run_sweep_partitioned", "run_sweep_rowsharded",
           "reset_run_stats", "run_stats", "record_fallback",
           "BINARY_METRICS", "MULTICLASS_METRICS", "REGRESSION_METRICS"]


# ---------------------------------------------------------------------------
# Fragment interpreters (traced inline inside the one fused program)
#
# Every interpreter takes an optional row-shard context ``rs = (axis_name,
# n_orig, n_data)`` (static).  With ``rs=None`` the trace is byte-identical
# to the replicated program.  With it, the interpreter's row axis holds ONE
# data shard of ``n_orig`` padded rows: the training kernels psum their
# cross-row reductions over ``axis_name`` (ops/linear, ops/trees, ops/mlp),
# on-device RNG draws happen at the ORIGINAL row count (shape-keyed Poisson/
# uniform draws must match the single-device stream bit-for-bit) and are then
# sliced to the local block, and all per-row state stays local.
# ---------------------------------------------------------------------------
def _rs_axis(rs) -> Optional[str]:
    return None if rs is None else rs[0]


def _local_rows(full, n_local: int, rs, axis: int = 0):
    """This shard's contiguous block of a full-row array drawn at n_orig.

    Zero-pads ``axis`` from n_orig up to ``n_data * n_local`` (padding rows
    carry zero weight everywhere downstream) and slices the block at
    ``axis_index * n_local`` — shard_map row shards are contiguous."""
    axis_name, _, n_data = rs
    pad = n_data * n_local - full.shape[axis]
    if pad:
        widths = [(0, 0)] * full.ndim
        widths[axis] = (0, pad)
        full = jnp.pad(full, widths)
    start = lax.axis_index(axis_name) * n_local
    return lax.dynamic_slice_in_dim(full, start, n_local, axis=axis)


def _fista_scores(frag, X, y, train_w, blob, classification: bool, rs=None):
    _, cis, max_iter, fit_intercept, off_l1, off_l2 = frag
    G = len(cis)
    l1 = blob[off_l1:off_l1 + G]
    l2 = blob[off_l2:off_l2 + G]
    ax = _rs_axis(rs)
    if classification:
        fit = L.fit_logistic_grid_folds_fista(X, y, train_w, l1, l2,
                                              max_iter=max_iter,
                                              fit_intercept=fit_intercept,
                                              axis_name=ax)
        z = jnp.einsum("nd,fgd->fgn", X, fit.coef) + fit.intercept[..., :1]
        return jax.nn.sigmoid(z)
    fit = L.fit_linear_grid_folds_fista(X, y, train_w, l1, l2,
                                        max_iter=max_iter,
                                        fit_intercept=fit_intercept,
                                        axis_name=ax)
    return jnp.einsum("nd,fgd->fgn", X, fit.coef) + fit.intercept[..., :1]


def _softmax_scores(frag, X, y, train_w, blob, k: int, rs=None):
    """Multiclass logistic: class probabilities [F, G, n, k]."""
    _, cis, max_iter, fit_intercept, off_l1, off_l2 = frag
    G = len(cis)
    l1 = blob[off_l1:off_l1 + G]
    l2 = blob[off_l2:off_l2 + G]
    fit = L.fit_softmax_grid_folds(X, y, train_w, l1, l2, num_classes=k,
                                   max_iter=max_iter,
                                   fit_intercept=fit_intercept,
                                   axis_name=_rs_axis(rs))
    z = jnp.einsum("nd,fgdk->fgnk", X, fit.coef) + fit.intercept[:, :, None, :]
    return jax.nn.softmax(z, axis=-1)


def _newton_scores(frag, X, y, train_w, blob, rs=None):
    _, cis, max_iter, fit_intercept, off_l2 = frag
    l2 = blob[off_l2:off_l2 + len(cis)]
    fit = L.fit_logistic_grid_folds_newton(X, y, train_w, l2,
                                           max_iter=max_iter,
                                           fit_intercept=fit_intercept,
                                           axis_name=_rs_axis(rs))
    z = jnp.einsum("nd,fgd->fgn", X, fit.coef) + fit.intercept[..., :1]
    return jax.nn.sigmoid(z)


def _svc_scores(frag, X, y, train_w, blob, rs=None):
    """Squared-hinge SVC: the host path emits raw margins but NO probability
    (Spark LinearSVC parity), so its evaluator sees the HARD prediction as
    the score — the fused score reproduces exactly that 0/1 score."""
    _, cis, max_iter, fit_intercept, off_l2 = frag
    l2 = blob[off_l2:off_l2 + len(cis)]
    fit = L.fit_svc_grid_folds(X, y, train_w, l2, max_iter=max_iter,
                               fit_intercept=fit_intercept,
                               axis_name=_rs_axis(rs))
    z = jnp.einsum("nd,fgd->fgn", X, fit.coef) + fit.intercept[..., :1]
    return (z >= 0.0).astype(jnp.float32)


def _mlp_scores(frag, X, y, train_w, blob, full_prob: bool = False, rs=None):
    """Batched MLP: p(class 1) — or the full [F, G, n, k] distribution."""
    from . import mlp as M

    _, cis, layers, max_iter, off_lr, off_seed = frag
    G = len(cis)
    lrs = blob[off_lr:off_lr + G]
    seeds = blob[off_seed:off_seed + G].astype(jnp.int32)
    params = M.fit_mlp_grid_folds(X, y, train_w, lrs, seeds,
                                  layers=layers, max_iter=max_iter,
                                  axis_name=_rs_axis(rs))
    _, prob, _ = M.predict_mlp_grid(params, X)
    return prob if full_prob else prob[..., 1]


def _forest_group_scores(group, xbs, y, train_w, blob, out_c: int, rs=None):
    """One static forest group -> mean leaf vectors [F, Gc, n, c].

    Grouping (builder side) keys on (depth, n_trees, n_bins, frac, rate,
    bootstrap, seed), so ONE (bootstrap, feature-mask) draw — keyed exactly
    as ``fit_arrays`` keys it — serves every (fold, candidate) of the group,
    matching the legacy per-candidate path draw-for-draw.
    """
    (cis, depth, n_trees, xb_idx, n_bins, frac, rate, bootstrap, seed,
     frontier, exact_cap, chunk, off_mcw, off_mig) = group
    Xb = xbs[xb_idx]
    n, d = Xb.shape
    F = train_w.shape[0]
    Gc = len(cis)
    kb, kf = Tr.rng_keys(seed)
    if rs is None:
        boot = Tr.bootstrap_weights(kb, n, n_trees, bootstrap, rate)  # [T, n]
    else:
        # Poisson draws are shape-keyed: parity with the single-device launch
        # requires drawing the FULL [T, n_orig] stream, then slicing this
        # shard's contiguous row block (padding rows get fresh draws that are
        # zeroed by the padded train_w)
        boot = _local_rows(
            Tr.bootstrap_weights(kb, rs[1], n_trees, bootstrap, rate),
            n, rs, axis=1)
    fm = Tr.feature_masks(kf, d, n_trees, frac)                   # [T, d]
    g = -y[:, None] if out_c == 1 else -jax.nn.one_hot(
        y.astype(jnp.int32), out_c, dtype=jnp.float32)
    h = jnp.ones_like(y)

    mcw = blob[off_mcw:off_mcw + Gc]
    mig = blob[off_mig:off_mig + Gc]
    # tree population: (fold, candidate, tree) -> [F*Gc*T, n]
    wt = jnp.broadcast_to(boot[None, None] * train_w[:, None, None, :],
                          (F, Gc, n_trees, n)).reshape(F * Gc * n_trees, n)
    mcw_t = jnp.tile(jnp.repeat(mcw, n_trees), F)
    mig_t = jnp.tile(jnp.repeat(mig, n_trees), F)
    fm_t = jnp.tile(fm, (F * Gc, 1))
    TT = F * Gc * n_trees
    pad = (-TT) % chunk
    if pad:  # zero-weight padding trees grow nothing and are sliced off
        wt = jnp.concatenate([wt, jnp.zeros((pad, n), jnp.float32)])
        fm_t = jnp.concatenate([fm_t, jnp.ones((pad, d), jnp.float32)])
        mcw_t = jnp.concatenate([mcw_t, jnp.ones(pad, jnp.float32)])
        mig_t = jnp.concatenate([mig_t, jnp.zeros(pad, jnp.float32)])

    def one_chunk(args):
        wts, fms, mcws, migs = args
        lam = jnp.full(wts.shape[0], 1e-6, jnp.float32)
        gam = jnp.zeros(wts.shape[0], jnp.float32)
        tree, row_node = Tr.grow_forest(
            Xb, g, h, wts, fms, depth, n_bins, frontier,
            reg_lambda_t=lam, gamma_t=gam, mcw_t=mcws, mig_t=migs,
            exact_cap=exact_cap, return_row_node=True,
            axis_name=_rs_axis(rs))
        # growth routes EVERY row (weights only gate histograms), so
        # row_node already holds each row's leaf — reading leaf_val there
        # replaces the depth-step pointer walk that dominated the fragment
        # (measured 123-692 ms walk vs ~20 ms take at 900 trees)
        c = tree.leaf_val.shape[-1]
        return jnp.take_along_axis(
            tree.leaf_val, row_node[:, :, None].repeat(c, axis=2), axis=1)

    preds = lax.map(one_chunk, (wt.reshape(-1, chunk, n),
                                fm_t.reshape(-1, chunk, d),
                                mcw_t.reshape(-1, chunk),
                                mig_t.reshape(-1, chunk)))
    preds = preds.reshape((-1,) + preds.shape[2:])[:TT]       # [TT, n, c]
    return preds.reshape(F, Gc, n_trees, n, -1).mean(axis=2)  # [F, Gc, n, c]


def _gbt_group_scores(group, xbs, y, train_w, blob, loss: str, out_c: int,
                      rs=None):
    """One static boosting group -> final margins [F, Gc, n, c]."""
    (cis, rounds, depth, xb_idx, n_bins, subsample, colsample, seed,
     frontier, exact_cap, fold_base, trees_per_round, off_eta, off_lam,
     off_gam, off_mcw, off_mig) = group
    Xb = xbs[xb_idx]
    n, d = Xb.shape
    F = train_w.shape[0]
    Gc = len(cis)
    ax = _rs_axis(rs)
    ks, kf = Tr.rng_keys(seed)
    if rs is None:
        rw = Tr.subsample_weights(ks, n, rounds, subsample)
    else:  # full-stream draw then local slice — see _forest_group_scores
        rw = _local_rows(Tr.subsample_weights(ks, rs[1], rounds, subsample),
                         n, rs, axis=1)
    fms = Tr.feature_masks(kf, d, rounds, colsample)

    eta = blob[off_eta:off_eta + Gc]
    lam = jnp.maximum(blob[off_lam:off_lam + Gc], 1e-6)
    gam = blob[off_gam:off_gam + Gc]
    mcw = blob[off_mcw:off_mcw + Gc]
    mig = blob[off_mig:off_mig + Gc]

    if fold_base:  # regression boosting starts from the fold's label mean
        base_f = (mesh_psum((y[None, :] * train_w).sum(1), ax)
                  / jnp.maximum(mesh_psum(train_w.sum(1), ax), 1e-12))
    else:
        base_f = jnp.zeros(F, jnp.float32)

    w_b = jnp.repeat(train_w, Gc, axis=0)              # [F*Gc, n]
    eta_b = jnp.tile(eta, F)
    lam_b = jnp.tile(lam, F)
    gam_b = jnp.tile(gam, F)
    mcw_b = jnp.tile(mcw, F)
    mig_b = jnp.tile(mig, F)
    base_b = jnp.repeat(base_f, Gc)

    if trees_per_round > 1:
        # round-collapsed: one K-wide forest step per rounds/K scan steps
        Fm = Tr._gbt_batch_impl(Xb, y, w_b, rw, fms, loss, rounds, depth,
                                n_bins, frontier, eta_b, lam_b, gam_b, mcw_b,
                                base_score_b=base_b, n_classes=out_c,
                                min_info_gain_b=mig_b, exact_cap=exact_cap,
                                axis_name=ax, trees_per_round=trees_per_round)
        return Fm.reshape(F, Gc, n, -1)

    def one(w, e, l, ga, mc, ba, mi):
        _, Fm = Tr._gbt_impl(Xb, y, w, rw, fms, loss, rounds, depth, n_bins,
                             frontier, e, l, ga, mc, ba, out_c,
                             min_info_gain=mi, exact_cap=exact_cap,
                             axis_name=ax)
        return Fm

    Fm = jax.vmap(one)(w_b, eta_b, lam_b, gam_b, mcw_b, base_b, mig_b)
    return Fm.reshape(F, Gc, n, -1)


def _frag_scores(frag, X, xbs, y, train_w, blob, problem, rs=None):
    """Returns (cis, scores [F, Gf, n] — or [F, Gf, n, k] multiclass)."""
    kind = frag[0]
    multiclass = isinstance(problem, tuple)
    classification = problem == "binary" or multiclass
    if kind == "fista":
        if multiclass:
            return frag[1], _softmax_scores(frag, X, y, train_w, blob,
                                            problem[1], rs=rs)
        return frag[1], _fista_scores(frag, X, y, train_w, blob,
                                      classification, rs=rs)
    if kind == "newton":
        return frag[1], _newton_scores(frag, X, y, train_w, blob, rs=rs)
    if kind == "svc":
        return frag[1], _svc_scores(frag, X, y, train_w, blob, rs=rs)
    if kind == "mlp":
        return frag[1], _mlp_scores(frag, X, y, train_w, blob,
                                    full_prob=multiclass, rs=rs)
    if kind == "forest":
        _, out_c, groups = frag
        cis_all, outs = [], []
        for grp in groups:
            dist = _forest_group_scores(grp, xbs, y, train_w, blob, out_c,
                                        rs=rs)
            # binary classification: 1-channel leaves ARE p(class=1);
            # regression: mean leaves are the prediction; multiclass keeps
            # the class-distribution leaves (argmax-equivalent unnormalized);
            # k=2-multiclass trains the SAME 1-channel binary kernel as the
            # legacy path and expands p -> [1-p, p] here
            if multiclass and dist.shape[-1] == 1:
                dist = jnp.concatenate([1.0 - dist, dist], axis=-1)
            outs.append(dist if multiclass else dist[..., 0])
            cis_all.extend(grp[0])
        return cis_all, jnp.concatenate(outs, axis=1)
    if kind == "gbt":
        _, loss, out_c, groups = frag
        cis_all, outs = [], []
        for grp in groups:
            Fm = _gbt_group_scores(grp, xbs, y, train_w, blob, loss, out_c,
                                   rs=rs)
            if loss == "softmax":
                outs.append(jax.nn.softmax(Fm, axis=-1))
            elif loss == "logistic":
                outs.append(jax.nn.sigmoid(Fm[..., 0]))
            else:  # squared: the margin IS the prediction
                outs.append(Fm[..., 0])
            cis_all.extend(grp[0])
        return cis_all, jnp.concatenate(outs, axis=1)
    raise ValueError(f"unknown sweep fragment {kind!r}")


def _all_scores(spec, X, xbs, y, train_w, blob, rs=None):
    problem, frags, strict = spec
    n = y.shape[0]
    F = train_w.shape[0]
    C = len(strict)
    if isinstance(problem, tuple):  # ("multiclass", k)
        scores = jnp.zeros((F, C, n, problem[1]), jnp.float32)
    else:
        scores = jnp.zeros((F, C, n), jnp.float32)
    for frag in frags:
        cis, sc = _frag_scores(frag, X, xbs, y, train_w, blob, problem, rs=rs)
        if isinstance(problem, tuple) and sc.ndim == 3:
            # binary-family fragment under a k=2 multiclass evaluator:
            # expand the class-1 score to the [p0, p1] plane
            sc = jnp.stack([1.0 - sc, sc], axis=-1)
        scores = scores.at[:, np.asarray(cis, np.int64)].set(sc)
    return scores


def _metrics_of(spec, y, scores, val_w):
    problem, _, strict = spec
    if isinstance(problem, tuple):
        y1 = jax.nn.one_hot(y.astype(jnp.int32), problem[1],
                            dtype=jnp.float32)
        return _multiclass_grid_metrics(y1, scores, val_w)
    if problem == "binary":
        return _binary_grid_metrics(y, scores, val_w,
                                    jnp.asarray(strict, jnp.float32))
    return _regression_grid_metrics(y, scores, val_w)


@functools.partial(jax.jit, static_argnames=("spec",))
def _run(spec, X, xbs, y, train_w, val_w, blob):
    return _metrics_of(spec, y, _all_scores(spec, X, xbs, y, train_w, blob),
                       val_w)


@functools.partial(jax.jit, static_argnames=("spec",))
def _run_scores(spec, X, xbs, y, train_w, blob):
    return _all_scores(spec, X, xbs, y, train_w, blob)


@functools.partial(jax.jit, static_argnames=("spec",))
def _run_metrics(spec, y, scores, val_w):
    return _metrics_of(spec, y, scores, val_w)


def _metrics_of_rs(spec, y, scores, val_w, rs):
    """Row-sharded metrics pass -> [F, C, M], identical on every data shard.

    The sum-shaped metrics could psum their accumulators, but AuROC/AuPR are
    rank-based and need the GLOBAL row order.  Reassembling the whole
    [F, C, n] score tensor at once would forfeit the 1/data_shards score-
    memory win, so the candidate axis runs under ``lax.map``: per candidate,
    all_gather this shard's [F, n_local] score block to [F, n_pad] (a
    transient), evaluate the single-candidate metric kernels on globally
    ordered rows, and move on.  Padding rows carry zero validation weight and
    the metric kernels already treat vm=0 rows as excluded.

    Candidate packing (``TMOG_SWEEP_PACK``): the map runs
    ``_metric_pack_size()`` candidates per step (inner ``vmap``), so the
    sequential step count drops from C to ``ceil(C / P)`` while each
    candidate's math is the untouched single-candidate kernel.  The
    candidate axis zero-pads up to a multiple of P (dummy lanes are
    sliced off; their scores are zeros and their outputs discarded)."""
    problem, _, strict = spec
    ax = rs[0]
    C = int(scores.shape[1])
    k = problem[1] if isinstance(problem, tuple) else 1
    P_pack = _metric_pack_size(C, int(scores.shape[0]),
                               int(scores.shape[2]) * int(rs[2]), k)

    def packed_map(body, xs):
        if P_pack <= 1:
            return lax.map(body, xs)
        pad = (-C) % P_pack

        def prep(a):
            if pad:
                a = jnp.concatenate(
                    [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)], 0)
            return a.reshape((-(-C // P_pack), P_pack) + a.shape[1:])

        out = lax.map(jax.vmap(body), jax.tree.map(prep, xs))
        return out.reshape((-1,) + out.shape[2:])[:C]

    y_full = mesh_all_gather(y, ax, axis=0)             # [n_pad]
    vw_full = mesh_all_gather(val_w, ax, axis=1)        # [F, n_pad]
    if isinstance(problem, tuple):
        y1 = jax.nn.one_hot(y_full.astype(jnp.int32), problem[1],
                            dtype=jnp.float32)

        def one_mc(sc):                                 # sc [F, n_local, k]
            sf = mesh_all_gather(sc, ax, axis=1)        # [F, n_pad, k]
            return jax.vmap(_multiclass_one, in_axes=(None, 0, 0))(
                y1, sf, vw_full)                        # [F, M]

        out = packed_map(one_mc, jnp.moveaxis(scores, 1, 0))
        return jnp.moveaxis(out, 0, 1)                  # [F, C, M]
    if problem == "binary":
        def one_bin(args):
            sc, st = args                               # [F, n_local], f32
            sf = mesh_all_gather(sc, ax, axis=1)        # [F, n_pad]
            return jax.vmap(_binary_one, in_axes=(None, 0, 0, None))(
                y_full, sf, vw_full, st)                # [F, M]

        out = packed_map(one_bin, (jnp.moveaxis(scores, 1, 0),
                                   jnp.asarray(strict, jnp.float32)))
        return jnp.moveaxis(out, 0, 1)

    def one_reg(sc):
        sf = mesh_all_gather(sc, ax, axis=1)
        return jax.vmap(_regression_one, in_axes=(None, 0, 0))(
            y_full, sf, vw_full)

    out = packed_map(one_reg, jnp.moveaxis(scores, 1, 0))
    return jnp.moveaxis(out, 0, 1)


@functools.partial(jax.jit, static_argnames=("spec", "mesh", "n_orig"))
def _run_rs(spec, mesh, n_orig, X, xbs, y, train_w, val_w, blob):
    """ONE model column's fused program, row-sharded over its (data,) submesh.

    Every array argument must be committed with the matching sharding (rows
    over DATA_AXIS for X/xbs/y, axis 1 for the fold-weight matrices, blob
    replicated).  Inside shard_map each device sees one contiguous row block
    of n_pad/n_data rows; the interpreters' cross-row reductions become psums
    over the data axis (normal-equation blocks, gradient/hessian histograms,
    fold accumulators) while per-candidate state stays local, and the metric
    pass reassembles global row order per candidate.  ``n_orig`` is static so
    the RNG parity slices bake in.  NOTE: no SPLIT_METRICS two-launch variant
    here — the lax.map over candidates already bounds the metric transient to
    one [F, n_pad] block."""
    ax = mesh_mod.DATA_AXIS
    n_data = int(mesh.shape[ax])
    rs = (ax, n_orig, n_data)

    def local(Xl, xbs_l, yl, twl, vwl, bl):
        scores = _all_scores(spec, Xl, xbs_l, yl, twl, bl, rs=rs)
        return _metrics_of_rs(spec, yl, scores, vwl, rs)

    return _shard_map(
        local, mesh=mesh,
        in_specs=(P(ax), P(ax), P(ax), P(None, ax), P(None, ax), P()),
        out_specs=P(), **_no_check)(X, xbs, y, train_w, val_w, blob)


#: above this many score ELEMENTS the sweep runs as TWO launches (scores,
#: then metrics): compiling family training together with the metric sort
#: pipeline into one program killed the tunneled TPU worker at 500k x 33
#: candidates even though each half runs fine alone (round-5 bisection); at
#: small n the single launch saves a ~25 ms round trip.
SPLIT_METRICS_ELEMS = 20_000_000


def _sweep_pack() -> bool:
    """Candidate-packed launches (``TMOG_SWEEP_PACK``, default off).

    On: the launcher builds cost-model-sized launch packs
    (``parallel.spec_partition.launch_packs``) instead of one monolithic
    queue per device, and the row-sharded metric pass evaluates
    ``_metric_pack_size()`` candidates per ``lax.map`` step instead of one
    — fewer sequential dispatches, bit-identical per-candidate math."""
    from ..utils.env import env_flag

    return env_flag("TMOG_SWEEP_PACK", False)


def _gbt_pipeline() -> bool:
    """Cross-device GBT pipelining (``TMOG_GBT_PIPELINE``, default off).

    On (and > 1 shard): every partitioned shard forces the two-launch
    stage split and dispatch is double-buffered across shards — shard i
    holds its metrics (stage 2) dispatch until shard i+1's training/
    histogram launch (stage 1) is in flight, so scoring on one device
    overlaps histogram building on the next.  The hedge deadline clock
    starts AFTER the pipelined prologue (stage compiles + stage-1
    dispatch + the neighbor handshake)."""
    from ..utils.env import env_flag

    return env_flag("TMOG_GBT_PIPELINE", False)


def _metric_pack_size(C: int, F: int, n_pad: int, k: int = 1) -> int:
    """Candidates per packed metric-map step (row-sharded path).

    The per-candidate transient of ``_metrics_of_rs`` is one gathered
    [F, n_pad(, k)] score block; packing P candidates per ``lax.map``
    step multiplies that transient by P, so P is the largest count whose
    transients fit the ``TMOG_PACK_HBM_MB`` budget (the same analytic
    budget ``launch_packs`` bins by).  Returns 1 unless
    ``TMOG_SWEEP_PACK`` is on — the exact historical one-candidate map.
    Pure function of static shapes, so the traced program and the
    launcher's host-side telemetry agree by construction."""
    if C <= 1 or not _sweep_pack():
        return 1
    from ..utils.env import env_float

    budget = env_float("TMOG_PACK_HBM_MB", 2048.0) * 1e6
    per_cand = max(float(F) * float(n_pad) * max(int(k), 1) * 4.0, 1.0)
    return int(max(1, min(int(C), budget // per_cand)))


def _trace_knobs() -> Tuple:
    """Trace-affecting env knobs baked into compiled programs — part of
    every AOT cache key, so flipping a knob mid-process re-lowers instead
    of silently reusing the other configuration's executable (the jit
    paths still need ``jax.clear_caches()``; see
    tests/test_hist_subtract_parity.py)."""
    return (Tr._hist_subtract(), Tr._hist_bf16(), Tr._bf16_hist_acc(),
            _sweep_pack())


#: kernel trace events (hist-subtraction savings) per (spec, n_rows).  jit
#: caches traces, so only the FIRST execution of a program re-runs the
#: Python-level ``record_trace_event`` calls — later calls (and ``.lower``
#: for cost analysis) see an empty trace.  run_sweep captures the first
#: trace here and replays it into utils/flops on every call, matching the
#: per-call replay the AOT shard paths get from their cached (compiled,
#: events) pairs.
_TRACE_EVENT_CACHE: Dict[Tuple, Tuple] = {}


def _replay_trace_events(spec, n: int, colls) -> None:
    # keyed on the trace-shaping flags too: flipping TMOG_HIST_SUBTRACT /
    # TMOG_BF16_HIST mid-process must not replay the other
    # configuration's savings
    key = (spec, int(n), Tr._hist_subtract(), Tr._bf16_hist_acc())
    events = tuple(c for c in colls
                   if c[0] in ("hist_subtracted", "gbt_chain", "bf16_hist"))
    if events:
        _TRACE_EVENT_CACHE[key] = events
    else:
        events = _TRACE_EVENT_CACHE.get(key, ())
    flops.record_collectives(events)


def run_sweep(spec, X, xbs: Tuple, y, train_w, val_w, blob):
    """Execute a fused sweep program; returns device metrics [F, C, M].

    ``spec`` must be a hashable static tuple (see module docstring); arrays
    may be host or device (device-resident via utils.devcache recommended).
    """
    C = len(spec[2])
    n = int(np.asarray(y).shape[0] if not hasattr(y, "shape") else y.shape[0])
    F = train_w.shape[0]
    k = spec[0][1] if isinstance(spec[0], tuple) else 1
    split = F * C * n * k > SPLIT_METRICS_ELEMS
    # whole-launch checkpoint (the single-device sweep is one work unit)
    _ck = _ckpt.store()
    ck_key = None
    if _ck.enabled:
        ck_key = _ckpt.content_key(
            "sweep_launch", spec, blob, *_ckpt.host_key_part(),
            _ckpt.data_fingerprint(X),
            _ckpt.data_fingerprint(y), _ckpt.data_fingerprint(train_w),
            _ckpt.data_fingerprint(val_w))
        hit = _ck.load("sweep_launch", ck_key)
        if hit is not None:
            _sweep_scope.inc("checkpoint_skips")
            _sweep_scope.append("launches", {
                "shards": 1, "candidates": C, "checkpoint": "hit"})
            return jnp.asarray(hit[0]["metrics"])
    entry = {"shards": 1, "candidates": C, "split": bool(split)}
    chain = _spec_gbt_chain(spec)
    if chain:
        entry["gbt_chain"] = chain
    _sweep_scope.append("launches", entry)
    with trace.span("sweep.launch", shards=1, candidates=C,
                    split=bool(split)):
        if chain:
            trace.instant("gbt.chain", steps=chain["steps"],
                          levels=chain["levels"])
        _lg = _ledger.get()

        def _dispatch(ctl=None):
            _inject.maybe_fail("sweep.dispatch", key="fused")
            if ctl is not None:
                ctl.mark_dispatch()
            _t0 = _lg.now()
            if split:
                with trace.span("sweep.dispatch", shards=1, split=True):
                    with mesh_mod.trace_collectives() as colls:
                        scores = _run_scores(spec, X, tuple(xbs), y, train_w,
                                             blob)
                    res = _run_metrics(spec, y, scores, val_w)
            else:
                scores = None
                with trace.span("sweep.dispatch", shards=1, split=False):
                    with mesh_mod.trace_collectives() as colls:
                        res = _run(spec, X, tuple(xbs), y, train_w, val_w,
                                   blob)
            return res, scores, tuple(colls), _lg.now() - _t0

        hedged = False
        if _hedge.enabled():
            # same-slot redundant dispatch: this path's dispatch is async,
            # so the deadline only fires when the dispatch CALL itself
            # stalls (an injected delay, a hung transfer) — the duplicate
            # re-enters the jit cache and whichever returns first wins
            feat0 = _shard_feat(spec, n, int(X.shape[1]), F)
            deadline = _hedge.shard_deadline(_feat_units(feat0), feat0)

            def _waste(task, slot, wall, result):
                _sweep_scope.inc("hedge_wasted_s", wall)
                entry.setdefault("hedges", []).append(
                    {"shard": 0, "wall_s": round(wall, 4), "wasted": True})
                lg = _ledger.get()
                if lg.enabled:
                    lg.launch("sweep.run_scores+metrics" if split
                              else "sweep.run",
                              wall_s=wall, flops=0.0, bytes=0.0,
                              families=_launch_families(
                                  spec, n, int(X.shape[1]), F),
                              shard=0, wasted=True)

            def _attempt(task, slot, ctl):
                if ctl.attempt > 0:
                    with trace.span("sweep.hedge", shard=0,
                                    attempt=ctl.attempt):
                        return _dispatch(ctl)
                return _dispatch(ctl)

            winners, hstats = _hedge.run_hedged(
                1, 1, _attempt, [deadline], same_slot=True,
                on_hedge=lambda *a: _sweep_scope.inc("hedges_fired"),
                on_waste=_waste)
            (out, scores, colls, _lwall), _slot, att_no, _awall = winners[0]
            hedged = att_no > 0
            if hstats["hedges_fired"]:
                entry["hedges_fired"] = hstats["hedges_fired"]
        else:
            out, scores, colls, _lwall = _dispatch()
        _replay_trace_events(spec, n, colls)
        if split:
            with trace.span("sweep.account", fn="sweep.run_scores+metrics"):
                costs = [
                    flops.record("sweep.run_scores", _run_scores, spec, X,
                                 tuple(xbs), y, train_w, blob),
                    flops.record("sweep.run_metrics", _run_metrics, spec, y,
                                 scores, val_w)]
            kernel = "sweep.run_scores+metrics"
        else:
            with trace.span("sweep.account", fn="sweep.run"):
                costs = [flops.record("sweep.run", _run, spec, X, tuple(xbs),
                                      y, train_w, val_w, blob)]
            kernel = "sweep.run"
        if _lg.enabled:
            # dispatch is async on this path (nothing gathers here), so the
            # wall is the dispatch span only — classification still holds
            # (a tiny wall reads launch-bound, which is the truth for a
            # launch whose device time we haven't observed yet)
            costs = [c for c in costs if c]
            _lg.launch(kernel, wall_s=_lwall,
                       flops=sum(c.get("flops", 0.0) for c in costs),
                       bytes=sum(c.get("bytes_accessed", 0.0)
                                 for c in costs),
                       families=_launch_families(spec, n, int(X.shape[1]),
                                                 F),
                       shard=0, split=bool(split),
                       **({"hedged": True} if hedged else {}))
        if ck_key is not None:
            with trace.span("sweep.checkpoint", candidates=C):
                _ck.save("sweep_launch", ck_key,
                         {"metrics": np.asarray(out)},
                         meta={"candidates": C, "split": bool(split)})
        return out


# ---------------------------------------------------------------------------
# Multi-chip execution: one sub-spec program per mesh ``model`` device
# ---------------------------------------------------------------------------
#: sweep launch telemetry since the last ``reset_run_stats`` — one entry per
#: ``run_sweep`` ({"shards": 1, ...}) / ``run_sweep_partitioned`` call
#: ({"shards": k, "per_shard": [...], ...}); the bench and the multichip
#: dryrun read it to report ``sweep_shards`` + per-shard wall/compile times.
#: Storage lives in the central obs registry (scope "sweep");
#: ``run_stats()`` below is the backward-compatible view over it, and is
#: also what ``obs.snapshot()["sweep"]`` reports.
_sweep_scope = obs_registry.scope("sweep", defaults={
    "launches": [], "fallbacks": [], "compiles": 0, "compile_s": 0.0,
    "pruned_candidates": 0, "full_candidates": 0, "checkpoint_skips": 0,
    "hedges_fired": 0, "hedge_wasted_s": 0.0, "asha_rungs": [],
    "sweep_pack_count": 0, "launches_avoided": 0})
obs_registry.register_provider("sweep", lambda: run_stats())

#: per-(name, spec, device, arg-signature) AOT executables.  jit's own cache
#: would recompile nothing either, but going through ``.lower().compile()``
#: explicitly (a) lets the thread pool compile the per-shard programs
#: CONCURRENTLY — the warmup is one compile's wall, not the sum (the 8.1 s
#: single-chip warmup of BENCH_r05 was the sum of fragment compiles) — and
#: (b) gives an executable whose ``cost_analysis`` flops.record_compiled can
#: read without re-lowering.
_aot_cache: Dict[Tuple, Any] = {}
_aot_lock = threading.Lock()

#: one-shot wiring of jax's persistent compilation cache before the first
#: sweep compile — a restarted process re-lowers but XLA reloads the
#: compiled artifact from ``TMOG_COMPILE_CACHE`` (TPU/GPU; the CPU backend
#: refuses its own entries, which is why serving persists serialized
#: executables via ``serve/compile_cache`` instead)
_cache_wired = False


def _wire_compile_cache() -> None:
    global _cache_wired
    if _cache_wired:
        return
    with _aot_lock:
        if _cache_wired:
            return
        _cache_wired = True
    try:
        from ..utils.backend import enable_compile_cache

        enable_compile_cache()
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        record_fallback("compile_cache_unavailable", error=repr(e))


def reset_run_stats() -> None:
    _sweep_scope.reset()


def record_fallback(reason: str, **detail) -> None:
    """Note that a launch declined row-sharding (or fusion) and why.

    The graceful-degradation contract: when rows are too few for the data
    axis or a custom estimator blocks fusion, the validator routes through
    the replicated path and RECORDS the reason here instead of erroring —
    ``run_stats()['fallbacks']`` is the audit trail.  Delegates to the one
    central recorder (obs.registry.record_fallback, domain="sweep")."""
    obs_registry.record_fallback("sweep", reason, **detail)


def run_stats() -> Dict[str, Any]:
    """Aggregate view of launches since the last reset (host-side stats)."""
    launches = _sweep_scope.list("launches")
    return {"launches": launches,
            "sweep_shards": max((e["shards"] for e in launches), default=0),
            "data_shards": max((e.get("data_shards", 1) for e in launches),
                               default=0),
            # longest post-collapse boosting chain any launch dispatched
            "gbt_chain_steps": max(
                (e.get("gbt_chain", {}).get("steps", 0) for e in launches),
                default=0),
            "gbt_chain_levels": max(
                (e.get("gbt_chain", {}).get("levels", 0) for e in launches),
                default=0),
            # AOT compile telemetry (cache misses since reset); the per-shape
            # compile-count feature of the learned-cost-model training row
            "compiles": _sweep_scope.get("compiles"),
            "compile_s": _sweep_scope.get("compile_s"),
            # warm-start retrain accounting (continual loop): how many grid
            # candidates actually swept vs the cold grid's full count
            "pruned_candidates": _sweep_scope.get("pruned_candidates"),
            "full_candidates": _sweep_scope.get("full_candidates"),
            # shards/launches skipped because a TMOG_CHECKPOINT_DIR
            # checkpoint from a previous (possibly killed) run covered them
            "checkpoint_skips": _sweep_scope.get("checkpoint_skips"),
            # straggler defense: duplicate dispatches fired past their
            # deadline, and the losers' discarded wall (resilience/hedge)
            "hedges_fired": _sweep_scope.get("hedges_fired"),
            "hedge_wasted_s": _sweep_scope.get("hedge_wasted_s"),
            # candidate packing (TMOG_SWEEP_PACK): packed launches built
            # since reset, and sequential dispatches avoided vs the
            # one-launch-per-candidate baseline (record_packs + the
            # row-sharded metric map)
            "sweep_pack_count": _sweep_scope.get("sweep_pack_count"),
            "launches_avoided": _sweep_scope.get("launches_avoided"),
            # sequential non-overlapped GBT launch-levels on the critical
            # path: per launch the pipelined effective chain
            # (gbt_chain_eff, measured dispatch-window overlap) when
            # present, else the full dependency chain — knobs off this
            # EQUALS gbt_chain_levels (the bench's historical
            # gbt_sequential_launches number)
            "gbt_sequential_launches": max(
                (int((e.get("gbt_chain_eff") or e.get("gbt_chain", {}))
                     .get("levels", 0)) for e in launches), default=0),
            # ASHA search: one record per completed rung (search/asha)
            "asha_rungs": _sweep_scope.list("asha_rungs"),
            "fallbacks": _sweep_scope.list("fallbacks")}


def record_warm_start(pruned: int, full: int) -> None:
    """Stamp a warm-started sweep's pruned-vs-full candidate counts (called
    by the validator after the sweep so the fused path's scope reset cannot
    wipe them)."""
    _sweep_scope.set("pruned_candidates", int(pruned))
    _sweep_scope.set("full_candidates", int(full))


def record_packs(n_packs: int, n_candidates: int) -> None:
    """Stamp one packed dispatch's launch-count telemetry
    (``TMOG_SWEEP_PACK``): ``n_candidates`` candidates ran as ``n_packs``
    fused launches.  ``launches_avoided`` counts against the honest
    one-launch-per-candidate dispatch baseline (the legacy per-family
    path), the same basis ``sweep_pack_count`` packs are bounded by."""
    _sweep_scope.inc("sweep_pack_count", int(n_packs))
    _sweep_scope.inc("launches_avoided",
                     max(int(n_candidates) - int(n_packs), 0))


def record_rungs(rows) -> None:
    """Stamp the ASHA scheduler's per-rung records after the search (same
    post-sweep stamping contract as :func:`record_warm_start`: the fused
    path resets this scope on entry, so the scheduler accumulates rung
    rows locally and stamps them once at the end)."""
    _sweep_scope.set("asha_rungs", [dict(r) for r in rows])


def _aot(name: str, fn, spec, device, dyn_args) -> Tuple[Any, float, Tuple]:
    """AOT executable of ``fn`` for ``spec`` at these (device-committed)
    arguments + compile seconds (0.0 on cache hit) + the program's traced
    (kind, axis, bytes) event list (hist-subtraction savings etc., replayed
    into utils/flops per call).  All ``dyn_args`` must be committed to
    ``device`` so lowering bakes the placement in."""
    key = (name, spec, device, _trace_knobs(),
           flops._signature(dyn_args, {}))
    with _aot_lock:
        hit = _aot_cache.get(key)
    if hit is not None:
        return hit[0], 0.0, hit[1]
    _wire_compile_cache()
    t0 = time.perf_counter()
    with trace.span("sweep.compile", fn=name, device=str(device)):
        with mesh_mod.trace_collectives() as colls:
            def _compile():
                _inject.maybe_fail("sweep.compile", key=name)
                return fn.lower(spec, *dyn_args).compile()

            compiled = _retry.with_retry("sweep.compile", _compile)
    dt = time.perf_counter() - t0
    _sweep_scope.inc("compiles")
    _sweep_scope.inc("compile_s", dt)
    with _aot_lock:
        # a racing thread may have compiled the same key; keep the first
        hit = _aot_cache.setdefault(key, (compiled, tuple(colls)))
    return hit[0], dt, hit[1]


def _spec_gbt_chain(spec) -> Optional[Dict[str, int]]:
    """Longest sequential boosting chain in ``spec``: {"steps", "levels"} —
    scan steps and dependent tree levels AFTER round-collapse (gbt group
    index 11 = trees_per_round).  None when the spec has no gbt fragment.
    This is the critical-path telemetry the bench reports as
    ``gbt_sequential_launches``."""
    steps = levels = 0
    for frag in spec[1]:
        if frag[0] != "gbt":
            continue
        for g in frag[3]:
            k = max(int(g[11]), 1)
            s = -(-int(g[1]) // k)
            steps = max(steps, s)
            levels = max(levels, s * int(g[2]))
    if steps == 0:
        return None
    return {"steps": steps, "levels": levels}


def _max_gbt_chain(specs) -> Optional[Dict[str, int]]:
    chains = [c for c in (_spec_gbt_chain(s) for s in specs) if c]
    if not chains:
        return None
    return {"steps": max(c["steps"] for c in chains),
            "levels": max(c["levels"] for c in chains)}


def _shard_feat(spec, n, d, F, data_shards=1, rows_local=None):
    """Static fragment-shape features of one shard's sub-spec, stamped into
    the per-shard launch telemetry so recorded JSONL rows are
    self-describing cost-model training rows (costmodel/features.py reads
    them back offline).  Telemetry must never kill the launch: any failure
    returns None and the entry simply has no ``feat``."""
    try:
        from ..costmodel.features import shard_feature_dict

        return shard_feature_dict(spec, n, d, F, data_shards=data_shards,
                                  rows_local=rows_local)
    except Exception:
        return None


def _feat_units(feat) -> float:
    """Total analytic cost units of one shard's feature dict (the
    calibration basis ``resilience.health`` prices deadlines in)."""
    if not feat:
        return 0.0
    try:
        from ..costmodel.features import family_units

        return float(sum(family_units(feat).values()))
    except Exception:
        return 0.0


#: costmodel family names -> the ledger/report labels the paper uses
_FAM_LABEL = {"linear": "LR", "mlp": "MLP", "forest": "RF", "gbt": "XGB"}
_fam_cache: Dict[Tuple, Dict[str, float]] = {}


def _launch_families(spec, n, d, F) -> Dict[str, float]:
    """Family label -> fraction of one launch's work, from the costmodel's
    per-family unit estimates (the PR-4 per-family lowering split) — how the
    launch ledger splits a mixed-family launch's FLOPs/bytes/wall.  Cached
    per (spec, n, d, F); degrades to a single "sweep" bucket on any failure
    (telemetry must never kill the launch)."""
    key = (spec, int(n), int(d), int(F))
    hit = _fam_cache.get(key)
    if hit is not None:
        return dict(hit)
    fams: Dict[str, float] = {}
    try:
        from ..costmodel.features import FAMILIES, family_units

        feat = _shard_feat(spec, n, d, F)
        if feat:
            units = family_units(feat)
            for f in FAMILIES:
                u = float(units.get(f, 0.0))
                if u > 0:
                    fams[_FAM_LABEL.get(f, f)] = u
    except Exception:
        fams = {}
    if not fams:
        fams = {"sweep": 1.0}
    tot = sum(fams.values())
    fams = {k: v / tot for k, v in fams.items()}
    _fam_cache[key] = fams
    return dict(fams)


def _stamp_cost_features(stat, costs) -> None:
    """Fold measured FLOPs/bytes into the shard's cost-model feature dict so
    recorded JSONL rows carry the memory-traffic features (FEATURE_NAMES
    tail) the learned cost model prices."""
    feat = stat.get("feat")
    if feat is None or not costs:
        return
    try:
        from ..costmodel.features import cost_feature_dict

        feat.update(cost_feature_dict(
            sum(c.get("flops", 0.0) for c in costs),
            sum(c.get("bytes_accessed", 0.0) for c in costs)))
    except Exception:
        pass


def _interval_cover(a: float, b: float, wins) -> float:
    """Total length of [a, b] covered by the union of intervals ``wins``."""
    segs = sorted((max(a, w0), min(b, w1)) for w0, w1 in wins
                  if w1 > a and w0 < b)
    tot, cur = 0.0, a
    for s0, s1 in segs:
        s0 = max(s0, cur)
        if s1 > s0:
            tot += s1 - s0
            cur = s1
    return tot


def _pipeline_chain_eff(shards, stats, n_shards: int
                        ) -> Optional[Dict[str, Any]]:
    """Effective sequential (non-overlapped) GBT chain of one pipelined
    launch: {"levels", "steps", "overlap_fraction"}.

    The f32 boosting chain is a true data dependency — its level count
    cannot shrink bit-identically — but under pipelined dispatch the
    chain-bearing shard's device window runs CONCURRENTLY with the other
    shards' windows, so the launch-critical-path accounting credits the
    measured overlap: ``eff = ceil(levels * (1 - cover))`` where
    ``cover`` is the fraction of the chain shard's dispatch->gather
    window covered by the union of the other shards' windows, floored at
    ``ceil(levels / n_shards)`` (perfect overlap still leaves the chain
    spread across the fleet).  Telemetry only — never raises; None when
    no chain shard carries a measured window."""
    try:
        import math

        best = None
        wins = [st.get("_win") for st in stats]
        for i, (sh, st) in enumerate(zip(shards, stats)):
            c = _spec_gbt_chain(sh.spec)
            win = wins[i]
            if not c or win is None or win[1] <= win[0]:
                continue
            a, b = win
            others = [w for j, w in enumerate(wins) if j != i and w]
            frac = min(max(_interval_cover(a, b, others) / (b - a), 0.0),
                       1.0)
            floor_div = max(int(n_shards), 1)
            cand = {
                "levels": max(int(math.ceil(c["levels"] * (1.0 - frac))),
                              -(-int(c["levels"]) // floor_div)),
                "steps": max(int(math.ceil(c["steps"] * (1.0 - frac))),
                             -(-int(c["steps"]) // floor_div)),
                "overlap_fraction": round(frac, 4)}
            if best is None or cand["levels"] > best["levels"]:
                best = cand
        return best
    except Exception:
        return None


def _shard_arrays(shard, dev, X, xbs, y, X_host, y_host, xb_bins):
    """Per-device copies of the shard's static arrays.

    With host identities available the copies go through utils.devcache
    (keyed per device), so repeated sweeps on the same dataset re-upload
    nothing; the binned matrices are a deterministic function of
    (X identity, n_bins), which is exactly their cache key.
    """
    if X_host is not None:
        Xd = devcache.device_array(X_host, np.float32, device=dev)
    else:
        Xd = jax.device_put(X, dev)
    if y_host is not None:
        yd = devcache.device_array(y_host, np.float32, device=dev)
    else:
        yd = jax.device_put(y, dev)
    xbs_d = []
    for i, xb in enumerate(xbs):
        if X_host is not None and xb_bins is not None:
            xbs_d.append(devcache.derived(
                X_host, ("sweep_xb_dev", int(xb_bins[i]), str(dev)),
                lambda xb=xb: jax.device_put(xb, dev)))
        else:
            xbs_d.append(jax.device_put(xb, dev))
    return Xd, tuple(xbs_d), yd


def run_sweep_partitioned(shards, X, xbs: Tuple, y, train_w, val_w,
                          n_candidates: int, devices,
                          X_host: Optional[np.ndarray] = None,
                          y_host: Optional[np.ndarray] = None,
                          xb_bins: Optional[Tuple[int, ...]] = None
                          ) -> np.ndarray:
    """Execute cost-balanced sub-spec programs, one per device, and gather.

    ``shards`` are ``parallel.spec_partition.ShardSpec``s (shard ``i`` runs
    on ``devices[i]``).  Each worker thread AOT-compiles its shard's program
    (concurrently — distinct cache keys never serialize on the lock) and
    dispatches it; JAX async dispatch overlaps execution across distinct
    devices with no SPMD constraint, so the heterogeneous per-shard fragment
    mixes are fine.  Each shard applies the ``SPLIT_METRICS_ELEMS``
    two-launch split to its OWN candidate count.  Returns host metrics
    [F, n_candidates, M] in the GLOBAL candidate order.
    """
    F = int(train_w.shape[0])
    n = int(X_host.shape[0]) if X_host is not None else int(X.shape[0])
    d = int(X_host.shape[1]) if X_host is not None else int(X.shape[1])
    k = shards[0].spec[0][1] if isinstance(shards[0].spec[0], tuple) else 1
    t_all = time.perf_counter()
    # preemption-safe shard checkpoints: content-keyed on (sub-spec, global
    # candidate ids, hyperparam blob, data fingerprints) so a killed sweep
    # that restarts with the same inputs skips its completed shards
    _ck = _ckpt.store()
    ck_data = () if not _ck.enabled else (
        *_ckpt.host_key_part(),
        _ckpt.data_fingerprint(X_host if X_host is not None else X),
        _ckpt.data_fingerprint(y_host if y_host is not None else y),
        _ckpt.data_fingerprint(train_w), _ckpt.data_fingerprint(val_w))

    # cross-device GBT pipelining: one handshake event per shard, set once
    # that shard's stage-1 (training/histogram) launch is in flight
    pipelined = _gbt_pipeline() and len(shards) > 1
    pipe_evs = ([threading.Event() for _ in shards] if pipelined else None)

    def worker(shard, dev, idx, ctl=None):
        t0 = time.perf_counter()
        ck_key = None
        if _ck.enabled:
            ck_key = _ckpt.content_key(
                "sweep_shard", shard.spec, tuple(map(int, shard.cis)),
                shard.blob, *ck_data)
            hit = _ck.load("sweep_shard", ck_key)
            if hit is not None:
                # a checkpoint hit completes instantly, so it also
                # short-circuits any pending hedge for this shard — and
                # must still release the pipeline handshake so the
                # predecessor shard's stage 2 is not held back
                if pipe_evs is not None:
                    pipe_evs[idx].set()
                _sweep_scope.inc("checkpoint_skips")
                stat = {"device": str(dev), "candidates": len(shard.cis),
                        "predicted_cost": float(shard.cost),
                        "compile_s": 0.0, "split": False,
                        "checkpoint": "hit",
                        "wall_s": round(time.perf_counter() - t0, 4)}
                return hit[0]["metrics"], stat, []
        _deadline = None if ctl is None else ctl.deadline_s
        with trace.span("sweep.shard", device=str(dev), shard=idx,
                        candidates=len(shard.cis)):
            with trace.span("sweep.upload", device=str(dev), shard=idx):
                Xd, xbs_d, yd = _shard_arrays(shard, dev, X, xbs, y,
                                              X_host, y_host, xb_bins)
                tw = jax.device_put(jnp.asarray(train_w), dev)
                vw = jax.device_put(jnp.asarray(val_w), dev)
                bl = jax.device_put(jnp.asarray(shard.blob), dev)
            C_s = len(shard.cis)
            # the pipelined path NEEDS the two-launch stage split: the
            # scores/metrics boundary is where one shard's scoring can
            # overlap the next shard's histogram building
            split = pipelined or F * C_s * n * k > SPLIT_METRICS_ELEMS
            records = []
            win = None
            _lg = _ledger.get()
            if split:
                args_s = (Xd, xbs_d, yd, tw, bl)
                cs, dt_s, ev_s = _aot("sweep.run_scores", _run_scores,
                                      shard.spec, dev, args_s)
                _lt0 = _lg.now()
                if ctl is not None and not pipelined:
                    # deadline clock starts at dispatch (pipelined: the
                    # clock starts inside _go_split, after the prologue)
                    ctl.mark_dispatch()

                def _go_split():
                    _inject.maybe_fail("sweep.dispatch", key=str(dev))
                    with trace.span("sweep.dispatch", device=str(dev),
                                    shard=idx, split=True,
                                    pipelined=bool(pipelined)):
                        t_s1 = time.perf_counter()
                        scores = cs(*args_s)   # stage 1 in flight (async)
                        if pipelined:
                            pipe_evs[idx].set()
                        args_m = (yd, scores, vw)
                        # stage-2 AOT overlaps stage-1 execution: lowering
                        # reads only the pending scores' aval
                        cm, dt_m, ev_m = _aot("sweep.run_metrics",
                                              _run_metrics, shard.spec, dev,
                                              args_m)
                        if pipelined:
                            # double buffer: hold MY metrics dispatch until
                            # the NEXT shard's histogram launch is in its
                            # stream, so stage 2 here overlaps stage 1 there
                            if idx + 1 < len(pipe_evs):
                                pipe_evs[idx + 1].wait(timeout=60.0)
                            if ctl is not None:
                                # hedge clock starts AFTER the pipelined
                                # prologue (compiles + stage-1 dispatch +
                                # neighbor handshake) — a deadline that
                                # included the prologue would hedge on
                                # compile time, not device health
                                ctl.mark_dispatch()
                        return (cm(*args_m), args_m, cm, dt_m, ev_m, t_s1)

                out, args_m, cm, dt_m, ev_m, _ts1 = _retry.with_retry(
                    "sweep.dispatch", _go_split, deadline_s=_deadline)
                win = _ts1
                compile_s = dt_s + dt_m
                records = [("sweep.run_scores", cs, args_s, ev_s),
                           ("sweep.run_metrics", cm, args_m, ev_m)]
            else:
                args = (Xd, xbs_d, yd, tw, vw, bl)
                c, compile_s, ev = _aot("sweep.run", _run, shard.spec, dev,
                                        args)
                _lt0 = _lg.now()
                if ctl is not None:   # deadline clock starts at dispatch
                    ctl.mark_dispatch()

                def _go():
                    _inject.maybe_fail("sweep.dispatch", key=str(dev))
                    with trace.span("sweep.dispatch", device=str(dev),
                                    shard=idx, split=False):
                        return c(*args)

                out = _retry.with_retry("sweep.dispatch", _go,
                                        deadline_s=_deadline)
                records = [("sweep.run", c, args, ev)]
            # block in THIS thread only: other shards keep dispatching/running
            with trace.span("sweep.gather", device=str(dev),
                            shard=idx) as _gsp:
                out = np.asarray(out)
                _gsp.set(bytes=int(out.nbytes))
        t_done = time.perf_counter()
        stat = {"device": str(dev), "candidates": C_s,
                "predicted_cost": float(shard.cost),
                "compile_s": round(compile_s, 4), "split": bool(split),
                "wall_s": round(t_done - t0, 4)}
        if pipelined and win is not None:
            stat["pipelined"] = True
            # stage-1-dispatch -> gather-end device window; consumed (and
            # popped) by _pipeline_chain_eff's overlap accounting
            stat["_win"] = (win, t_done)
        if _lg.enabled:
            # dispatch start -> gather end: the full device round trip the
            # ledger row reports (gather blocks in this thread, so this IS
            # the launch's measured wall, compile/upload excluded)
            stat["launch_wall_s"] = _lg.now() - _lt0
        feat = _shard_feat(shard.spec, n, d, F)
        if feat is not None:
            # cost-model features for the new launch shapes (append-only
            # FEATURE_NAMES tail; 0.0 == the historical unpacked launch)
            feat["pack_size"] = float(C_s) if _sweep_pack() else 0.0
            feat["pipeline_depth"] = 2.0 if pipelined else 0.0
            stat["feat"] = feat
        if ck_key is not None:
            _ck.save("sweep_shard", ck_key, {"metrics": out},
                     meta={"candidates": C_s, "split": bool(split)})
            stat["checkpoint"] = "saved"
        return out, stat, records

    with trace.span("sweep.launch", shards=len(shards),
                    candidates=int(n_candidates)):
        chain = _max_gbt_chain([s.spec for s in shards])
        if chain:
            trace.instant("gbt.chain", steps=chain["steps"],
                          levels=chain["levels"])
        hedge_events: List[Dict[str, Any]] = []
        hedges_fired = 0
        if not _hedge.enabled():
            # TMOG_HEDGE=0: the original dispatch, bit-identical
            with ThreadPoolExecutor(max_workers=len(shards)) as pool:
                results = list(pool.map(worker, shards, devices,
                                        range(len(shards))))
            win_devs = list(devices)
        else:
            tr = _health.tracker()
            deadlines = []
            for shard in shards:
                feat = _shard_feat(shard.spec, n, d, F)
                # health calibration is fed shard.cost units below, so the
                # analytic prediction must query in the same basis (feat
                # units ride along for the learned cost model only)
                deadlines.append(
                    _hedge.shard_deadline(float(shard.cost), feat))

            def _attempt(task, slot, ctl):
                shard, dev = shards[task], devices[slot]
                try:
                    if ctl.attempt > 0:
                        with trace.span("sweep.hedge", shard=task,
                                        device=str(dev),
                                        attempt=ctl.attempt):
                            res = worker(shard, dev, task, ctl=ctl)
                    else:
                        res = worker(shard, dev, task, ctl=ctl)
                except Exception as exc:
                    tr.record_error(str(dev), repr(exc))
                    raise
                tr.record_success(str(dev))
                return res

            def _on_hedge(task, slot, attempt_no, reason):
                nonlocal hedges_fired
                hedges_fired += 1
                _sweep_scope.inc("hedges_fired")
                hedge_events.append({
                    "shard": task, "device": str(devices[slot]),
                    "attempt": attempt_no, "reason": reason})

            def _on_waste(task, slot, wall, result):
                # runs in the LOSER's thread, possibly after the sweep
                # returned — the winner's gather never waits for this
                _sweep_scope.inc("hedge_wasted_s", wall)
                shard = shards[task]
                stat_l = result[1] if isinstance(result, tuple) else None
                ev = {"shard": task, "device": str(devices[slot]),
                      "wall_s": round(wall, 4), "wasted": True}
                if isinstance(stat_l, dict):
                    ev["wall_s"] = stat_l.get("wall_s", ev["wall_s"])
                    if stat_l.get("feat") is not None:
                        ev["feat"] = stat_l["feat"]
                hedge_events.append(ev)
                tr.record_straggler(str(devices[slot]), float(shard.cost),
                                    wall)
                lg = _ledger.get()
                if lg.enabled:
                    lg.launch("sweep.run", wall_s=wall, flops=0.0,
                              bytes=0.0,
                              families=_launch_families(shard.spec, n, d,
                                                        F),
                              shard=task, device=str(devices[slot]),
                              wasted=True)

            winners, _hstats = _hedge.run_hedged(
                len(shards), len(devices), _attempt, deadlines,
                on_hedge=_on_hedge, on_waste=_on_waste,
                slot_ok=lambda s: tr.usable(devices[s]))
            results, win_devs = [], []
            for res, slot, att_no, _w in winners:
                if att_no > 0 and isinstance(res, tuple):
                    res[1]["hedged"] = True
                    res[1]["attempt"] = att_no
                results.append(res)
                win_devs.append(devices[slot])

        M = results[0][0].shape[-1]
        metrics = np.zeros((F, n_candidates, M), np.float32)
        per_shard = []
        _lg = _ledger.get()
        d = int(X_host.shape[1]) if X_host is not None else int(X.shape[1])
        for sidx, ((out, stat, records), shard, dev) in enumerate(
                zip(results, shards, win_devs)):
            metrics[:, np.asarray(shard.cis, np.int64), :] = out
            per_shard.append(stat)
            costs = []
            for name, compiled, args, events in records:
                cost = flops.record_compiled(name, compiled, args,
                                             device=dev)
                flops.record_collectives(events, device=dev)
                if cost:
                    costs.append(cost)
            _stamp_cost_features(stat, costs)
            if _lg.enabled and records:
                _lg.launch("sweep.run" if len(records) == 1
                           else "sweep.run_scores+metrics",
                           wall_s=stat.get("launch_wall_s",
                                           stat.get("wall_s", 0.0)),
                           flops=sum(c.get("flops", 0.0) for c in costs),
                           bytes=sum(c.get("bytes_accessed", 0.0)
                                     for c in costs),
                           families=_launch_families(shard.spec, n, d, F),
                           shard=sidx, device=str(dev),
                           **({"hedged": True} if stat.get("hedged")
                              else {}))
        if _hedge.enabled():
            # winners' measured walls feed the device-health EWMAs that
            # weight the NEXT partition (telemetry must never kill a sweep)
            try:
                _health.tracker().observe_launch([
                    (stat["device"], float(shard.cost),
                     float(stat.get("launch_wall_s")
                           or max(stat.get("wall_s", 0.0)
                                  - stat.get("compile_s", 0.0), 0.0)))
                    for (out, stat, records), shard in zip(results, shards)
                    if stat.get("checkpoint") != "hit"])
            except Exception:
                pass
    entry = {"shards": len(shards), "candidates": int(n_candidates),
             "wall_s": round(time.perf_counter() - t_all, 4),
             "per_shard": per_shard}
    if hedges_fired:
        entry["hedges_fired"] = hedges_fired
        entry["hedges"] = hedge_events
    if chain:
        entry["gbt_chain"] = chain
        if pipelined:
            eff = _pipeline_chain_eff(shards, per_shard, len(shards))
            if eff is not None:
                entry["gbt_chain_eff"] = eff
    for st in per_shard:
        st.pop("_win", None)
    if pipelined:
        entry["pipelined"] = True
        entry["pipeline_depth"] = 2
    _sweep_scope.append("launches", entry)
    return metrics


# ---------------------------------------------------------------------------
# Row-sharded execution: a (data x model) mesh holding ONE row shard per chip
# ---------------------------------------------------------------------------
def _aot_rs(spec, submesh, n_orig: int, dyn_args) -> Tuple[Any, float, Tuple]:
    """AOT executable of ``_run_rs`` + compile seconds + the program's traced
    (kind, axis, bytes) collective list (replayed into utils/flops per call).
    The collective trace is captured at lowering and cached WITH the
    executable, so steady-state calls replay it without re-tracing."""
    key = ("sweep.run_rs", spec, submesh, n_orig, _trace_knobs(),
           flops._signature(dyn_args, {}))
    with _aot_lock:
        hit = _aot_cache.get(key)
    if hit is not None:
        return hit[0], 0.0, hit[1]
    _wire_compile_cache()
    t0 = time.perf_counter()
    with trace.span("sweep.compile", fn="sweep.run_rs",
                    devices=len(np.asarray(submesh.devices).flat)):
        with mesh_mod.trace_collectives() as colls:
            def _compile():
                _inject.maybe_fail("sweep.compile", key="sweep.run_rs")
                return _run_rs.lower(spec, submesh, n_orig,
                                     *dyn_args).compile()

            compiled = _retry.with_retry("sweep.compile", _compile)
    dt = time.perf_counter() - t0
    _sweep_scope.inc("compiles")
    _sweep_scope.inc("compile_s", dt)
    with _aot_lock:
        # a racing thread may have compiled the same key; keep the first
        hit = _aot_cache.setdefault(key, (compiled, tuple(colls)))
    return hit[0], dt, hit[1]


def _rs_arrays(submesh, X, xbs, y, X_host, y_host, xb_bins):
    """Row-sharded placements of the dataset over one model column's submesh.

    Rows are zero-padded to a multiple of the data-shard count (padding rows
    carry zero fold weight) and laid out over DATA_AXIS.  With host
    identities available the placements cache through utils.devcache keyed on
    (host identity, submesh devices), so repeated sweeps re-upload nothing.
    Returns (X, xbs tuple, y, original row count).
    """
    mkey = tuple(str(d) for d in np.asarray(submesh.devices).flat)
    if X_host is not None:
        Xd, n_orig = devcache.derived(
            X_host, ("sweep_rs_X", mkey),
            lambda: mesh_mod.shard_rows(np.asarray(X_host, np.float32),
                                        submesh))
    else:
        Xd, n_orig = mesh_mod.shard_rows(np.asarray(X, np.float32), submesh)
    if y_host is not None:
        yd, _ = devcache.derived(
            y_host, ("sweep_rs_y", mkey),
            lambda: mesh_mod.shard_rows(np.asarray(y_host, np.float32),
                                        submesh))
    else:
        yd, _ = mesh_mod.shard_rows(np.asarray(y, np.float32), submesh)
    xbs_d = []
    for i, xb in enumerate(xbs):
        if X_host is not None and xb_bins is not None:
            xbs_d.append(devcache.derived(
                X_host, ("sweep_rs_xb", int(xb_bins[i]), mkey),
                lambda xb=xb: mesh_mod.shard_rows(np.asarray(xb),
                                                  submesh)[0]))
        else:
            xbs_d.append(mesh_mod.shard_rows(np.asarray(xb), submesh)[0])
    return Xd, tuple(xbs_d), yd, n_orig


def run_sweep_rowsharded(shards, X, xbs: Tuple, y, train_w, val_w,
                         n_candidates: int, mesh,
                         X_host: Optional[np.ndarray] = None,
                         y_host: Optional[np.ndarray] = None,
                         xb_bins: Optional[Tuple[int, ...]] = None
                         ) -> np.ndarray:
    """Execute the sweep on a 2-D (data, model) mesh: model column ``j``
    runs ``shards[j]``'s sub-spec program row-sharded over the column's
    devices.

    Composition with the cost-balanced model partitioning is by construction:
    each column is an independent SPMD program over its own (data,)-axis
    submesh — no cross-model communication — dispatched from its own worker
    thread exactly like ``run_sweep_partitioned`` dispatches single-device
    shards.  Within a column every device holds rows/data_shards of X (the
    1/data_shards peak-memory claim; see the launch entry's
    ``per_device_bytes``) and the fragment interpreters reduce over the
    ``data`` axis with psum'd normal-equation blocks / histograms / metric
    accumulators.  Returns host metrics [F, n_candidates, M] in the GLOBAL
    candidate order.
    """
    grid = np.asarray(mesh.devices)
    ax_d = list(mesh.axis_names).index(mesh_mod.DATA_AXIS)
    ax_m = list(mesh.axis_names).index(mesh_mod.MODEL_AXIS)
    grid = np.moveaxis(grid, (ax_d, ax_m), (0, 1))
    n_data = grid.shape[0]
    if len(shards) > grid.shape[1]:
        raise ValueError(f"{len(shards)} model shards > mesh model axis "
                         f"{grid.shape[1]}")
    F = int(train_w.shape[0])
    n_feat = int(X_host.shape[1]) if X_host is not None else int(X.shape[1])
    n_rows = int(X_host.shape[0]) if X_host is not None else int(X.shape[0])
    tw_host = np.asarray(train_w, np.float32)
    vw_host = np.asarray(val_w, np.float32)
    t_all = time.perf_counter()
    # shard checkpoints, as in run_sweep_partitioned; the key carries the
    # data-shard count because the launch layout is part of the artifact
    _ck = _ckpt.store()
    ck_data = () if not _ck.enabled else (
        ("rs", int(n_data)), *_ckpt.host_key_part(),
        _ckpt.data_fingerprint(X_host if X_host is not None else X),
        _ckpt.data_fingerprint(y_host if y_host is not None else y),
        _ckpt.data_fingerprint(tw_host), _ckpt.data_fingerprint(vw_host))

    def worker(shard, j, ctl=None):
        t0 = time.perf_counter()
        ck_key = None
        if _ck.enabled:
            ck_key = _ckpt.content_key(
                "sweep_shard", shard.spec, tuple(map(int, shard.cis)),
                shard.blob, *ck_data)
            hit = _ck.load("sweep_shard", ck_key)
            if hit is not None:
                # instant completion: short-circuits any pending hedge
                _sweep_scope.inc("checkpoint_skips")
                stat = {"devices": [str(d) for d in grid[:, j]],
                        "candidates": len(shard.cis),
                        "predicted_cost": float(shard.cost),
                        "compile_s": 0.0, "checkpoint": "hit",
                        "wall_s": round(time.perf_counter() - t0, 4)}
                return hit[0]["metrics"], stat, None
        submesh = Mesh(grid[:, j], (mesh_mod.DATA_AXIS,))
        with trace.span("sweep.shard", column=j, data_shards=int(n_data),
                        candidates=len(shard.cis)):
            with trace.span("sweep.upload", column=j):
                Xd, xbs_d, yd, n_orig = _rs_arrays(submesh, X, xbs, y,
                                                   X_host, y_host, xb_bins)
                n_pad = int(Xd.shape[0])
                fold_sh = NamedSharding(submesh,
                                        P(None, mesh_mod.DATA_AXIS))
                tw = jax.device_put(
                    mesh_mod.pad_to_multiple(tw_host, n_data, axis=1)[0],
                    fold_sh)
                vw = jax.device_put(
                    mesh_mod.pad_to_multiple(vw_host, n_data, axis=1)[0],
                    fold_sh)
                bl = jax.device_put(np.asarray(shard.blob, np.float32),
                                    NamedSharding(submesh, P()))
            args = (Xd, xbs_d, yd, tw, vw, bl)
            compiled, compile_s, colls = _aot_rs(shard.spec, submesh, n_orig,
                                                 args)
            _lg = _ledger.get()
            _lt0 = _lg.now()
            if ctl is not None:   # deadline clock starts at dispatch
                ctl.mark_dispatch()

            def _go():
                _inject.maybe_fail("sweep.dispatch", key=f"rs{j}")
                with trace.span("sweep.dispatch", column=j):
                    return compiled(*args)

            out = _retry.with_retry(
                "sweep.dispatch", _go,
                deadline_s=None if ctl is None else ctl.deadline_s)
            # block in THIS thread only: other columns keep
            # dispatching/running
            with trace.span("sweep.gather", column=j) as _gsp:
                out = np.asarray(out)
                _gsp.set(bytes=int(out.nbytes))
        label = ",".join(str(d) for d in grid[:, j])
        stat = {"devices": [str(d) for d in grid[:, j]],
                "candidates": len(shard.cis),
                "predicted_cost": float(shard.cost),
                "compile_s": round(compile_s, 4),
                "rows_local": n_pad // n_data,
                "wall_s": round(time.perf_counter() - t0, 4)}
        if _lg.enabled:
            stat["launch_wall_s"] = _lg.now() - _lt0
        feat = _shard_feat(shard.spec, n_orig, n_feat, F,
                           data_shards=int(n_data),
                           rows_local=n_pad // n_data)
        if feat is not None:
            k_mc = (shard.spec[0][1]
                    if isinstance(shard.spec[0], tuple) else 1)
            feat["pack_size"] = float(_metric_pack_size(
                len(shard.cis), F, n_pad, k_mc)) if _sweep_pack() else 0.0
            feat["pipeline_depth"] = 0.0
            stat["feat"] = feat
        if ck_key is not None:
            _ck.save("sweep_shard", ck_key, {"metrics": out},
                     meta={"candidates": len(shard.cis), "rowsharded": True})
            stat["checkpoint"] = "saved"
        return out, stat, ("sweep.run_rs", compiled, args, label, colls,
                           n_orig, n_pad)

    with trace.span("sweep.launch", shards=len(shards),
                    data_shards=int(n_data), rowsharded=True,
                    candidates=int(n_candidates)):
        chain = _max_gbt_chain([s.spec for s in shards])
        if chain:
            trace.instant("gbt.chain", steps=chain["steps"],
                          levels=chain["levels"])
        hedge_events: List[Dict[str, Any]] = []
        hedges_fired = 0
        if not _hedge.enabled():
            # TMOG_HEDGE=0: the original dispatch, bit-identical
            with ThreadPoolExecutor(max_workers=len(shards)) as pool:
                results = list(pool.map(worker, shards, range(len(shards))))
        else:
            # a column's program only runs on its own submesh, so hedges
            # are SAME-SLOT redundant dispatches (the duplicate re-enters
            # the AOT cache; first completion wins)
            deadlines = []
            for shard in shards:
                feat = _shard_feat(shard.spec, n_rows, n_feat, F,
                                   data_shards=int(n_data))
                # same unit basis as the health calibration (shard.cost)
                deadlines.append(
                    _hedge.shard_deadline(float(shard.cost), feat))

            def _attempt(task, slot, ctl):
                if ctl.attempt > 0:
                    with trace.span("sweep.hedge", column=task,
                                    attempt=ctl.attempt):
                        return worker(shards[task], task, ctl=ctl)
                return worker(shards[task], task, ctl=ctl)

            def _on_hedge(task, slot, attempt_no, reason):
                nonlocal hedges_fired
                hedges_fired += 1
                _sweep_scope.inc("hedges_fired")
                hedge_events.append({"shard": task, "attempt": attempt_no,
                                     "reason": reason})

            def _on_waste(task, slot, wall, result):
                _sweep_scope.inc("hedge_wasted_s", wall)
                stat_l = result[1] if isinstance(result, tuple) else None
                ev = {"shard": task, "wall_s": round(wall, 4),
                      "wasted": True}
                if isinstance(stat_l, dict):
                    ev["wall_s"] = stat_l.get("wall_s", ev["wall_s"])
                    if stat_l.get("feat") is not None:
                        ev["feat"] = stat_l["feat"]
                hedge_events.append(ev)
                lg = _ledger.get()
                if lg.enabled:
                    lg.launch("sweep.run_rs", wall_s=wall, flops=0.0,
                              bytes=0.0,
                              families=_launch_families(
                                  shards[task].spec, n_rows, n_feat,
                                  F),
                              shard=task,
                              device=",".join(str(dd)
                                              for dd in grid[:, task]),
                              wasted=True)

            winners, _hstats = _hedge.run_hedged(
                len(shards), len(shards), _attempt, deadlines,
                same_slot=True, on_hedge=_on_hedge, on_waste=_on_waste)
            results = []
            for res, _slot, att_no, _w in winners:
                if att_no > 0 and isinstance(res, tuple):
                    res[1]["hedged"] = True
                    res[1]["attempt"] = att_no
                results.append(res)

    M = results[0][0].shape[-1]
    metrics = np.zeros((F, n_candidates, M), np.float32)
    per_shard = []
    coll_agg: Dict[str, Dict[str, float]] = {}
    n_orig = n_pad = 0
    _lg = _ledger.get()
    _d_feat = int(X_host.shape[1]) if X_host is not None else int(X.shape[1])
    for j, ((out, stat, rec), shard) in enumerate(zip(results, shards)):
        metrics[:, np.asarray(shard.cis, np.int64), :] = out[:F]
        per_shard.append(stat)
        if rec is None:  # shard restored from checkpoint: nothing ran
            continue
        name, compiled, args, label, colls, n_orig, n_pad = rec
        cost = flops.record_compiled(name, compiled, args, device=label)
        flops.record_collectives(colls, device=label)
        _stamp_cost_features(stat, [cost] if cost else [])
        # packed metric map: ceil(C/P) sequential map steps instead of C
        # (same static formula the traced program used — the launch-count
        # telemetry and the compiled loop agree by construction)
        k_mc = shard.spec[0][1] if isinstance(shard.spec[0], tuple) else 1
        mp = _metric_pack_size(len(shard.cis), F, n_pad, k_mc)
        if mp > 1:
            stat["metric_pack"] = int(mp)
            record_packs(-(-len(shard.cis) // mp), len(shard.cis))
        if _lg.enabled:
            _lg.launch(name,
                       wall_s=stat.get("launch_wall_s",
                                       stat.get("wall_s", 0.0)),
                       flops=cost.get("flops", 0.0) if cost else 0.0,
                       bytes=(cost.get("bytes_accessed", 0.0)
                              if cost else 0.0),
                       families=_launch_families(shard.spec, n_orig, _d_feat,
                                                 F),
                       shard=j, device=label)
        for kind, axis, nbytes in colls:
            if kind in ("hist_subtracted", "gbt_chain", "bf16_hist"):
                continue  # kernel trace events, not mesh traffic
            agg = coll_agg.setdefault(axis, {"count": 0.0, "bytes": 0.0})
            agg["count"] += 1
            agg["bytes"] += nbytes
    d = int(X_host.shape[1]) if X_host is not None else int(X.shape[1])
    entry = {"shards": len(shards), "data_shards": int(n_data),
             "rowsharded": True, "candidates": int(n_candidates),
             "wall_s": round(time.perf_counter() - t_all, 4),
             "per_shard": per_shard,
             "collectives": coll_agg,
             # the 1/data_shards peak-memory claim, auditable: what ONE device
             # of a model column holds vs what a replicated launch would hold
             "per_device_bytes": {
                 "X": n_pad // n_data * d * 4,
                 "y": n_pad // n_data * 4,
                 "X_replicated": n_orig * d * 4,
                 "y_replicated": n_orig * 4}}
    if hedges_fired:
        entry["hedges_fired"] = hedges_fired
        entry["hedges"] = hedge_events
    if chain:
        entry["gbt_chain"] = chain
    _sweep_scope.append("launches", entry)
    return metrics
