"""Histogram-based decision-tree / forest / boosting kernels — pure XLA.

The reference gets trees from Spark MLlib (RandomForest/GBT/DecisionTree)
and the XGBoost C++ core over JNI (`build.gradle:90`,
core/.../impl/classification/OpXGBoostClassifier.scala:47).  On TPU the
idiomatic formulation is the *histogram method* with static shapes and no
per-row control flow (SURVEY §7 "Trees/GBT/XGBoost on TPU"):

- features are pre-quantized to ``n_bins`` integer bins (quantile sketch,
  Spark's maxBins analog),
- a tree is grown breadth-first, level by level, over a FIXED full binary
  heap of ``2^(max_depth+1)-1`` nodes; per level the (node, feature, bin)
  gradient histograms are built with ``segment_sum`` (one scatter per
  feature, vmapped) and the best split per node is a pure cumsum/argmax
  reduction — everything batchable on the VPU/MXU,
- rows carry a node id; the level update is a gather + compare, no branching,
- second-order (g, h) statistics make the same builder serve XGBoost-style
  boosting (Newton leaves), RF regression (g = -y: variance gain, mean
  leaves), and RF classification (g = -onehot(y): gini-equivalent gain,
  class-distribution leaves),
- a forest is ``vmap(grow_tree)`` over bootstrap row-weights and feature
  masks; boosting is ``lax.scan`` over rounds — so a whole RF trains as ONE
  XLA launch, and boosting compiles to a single fixed-trip loop.

Trees are stored as flat arrays (heap layout): ``split_feat`` (-1 = leaf),
``split_bin``, ``leaf_val[heap, c]`` — pytree-friendly and trivially
serializable.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


class Tree(NamedTuple):
    """One tree in heap layout; leading axes may batch trees/rounds."""

    split_feat: jax.Array  # i32[heap]  (-1 => leaf)
    split_bin: jax.Array   # i32[heap]  (go right if bin > split_bin)
    leaf_val: jax.Array    # f32[heap, c]


# ---------------------------------------------------------------------------
# Quantization (host side, once per fit) — Spark maxBins / XGBoost sketch
# ---------------------------------------------------------------------------
def quantize(X: np.ndarray, n_bins: int = 32) -> Tuple[np.ndarray, np.ndarray]:
    """Equi-depth binning: returns (X_binned i32[n, d], edges f32[d, n_bins-1]).

    Bin b holds values in (edges[b-1], edges[b]]; value <= edges[0] is bin 0;
    value > edges[-1] is bin n_bins-1.  Matches Spark's quantile-based
    continuous-feature splits (maxBins default 32).
    """
    X = np.asarray(X, np.float32)
    n, d = X.shape
    qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    edges = np.quantile(X, qs, axis=0).T.astype(np.float32)  # [d, n_bins-1]
    # deduplicate edges per feature to avoid empty bins producing NaN gains
    Xb = np.empty((n, d), np.int32)
    for j in range(d):
        Xb[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    return Xb, edges


def bin_with_edges(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Apply fitted edges to new data (scoring path)."""
    X = np.asarray(X, np.float32)
    n, d = X.shape
    Xb = np.empty((n, d), np.int32)
    for j in range(d):
        Xb[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    return Xb


# ---------------------------------------------------------------------------
# Tree growth
# ---------------------------------------------------------------------------
def _level_histograms(Xb, gw, hw, node_local, active, m: int, n_bins: int):
    """Per-(node, feature, bin) stats for one level.

    Xb: i32[n, d]; gw: f32[n, c]; hw: f32[n]; node_local: i32[n] in [0, m).
    Returns G [m, d, B, c], H [m, d, B].
    """
    B = n_bins
    base = jnp.where(active, node_local * B, m * B)  # overflow bucket for dead rows

    def per_feature(bins_j):
        seg = base + jnp.where(active, bins_j, 0)
        G = jax.ops.segment_sum(gw, seg, num_segments=m * B + 1)[:-1]  # [m*B, c]
        H = jax.ops.segment_sum(hw, seg, num_segments=m * B + 1)[:-1]
        return G, H

    G, H = jax.vmap(per_feature, in_axes=1, out_axes=0)(Xb)  # [d, m*B, ...]
    c = gw.shape[1]
    G = G.reshape(Xb.shape[1], m, B, c).transpose(1, 0, 2, 3)
    H = H.reshape(Xb.shape[1], m, B).transpose(1, 0, 2)
    return G, H


def grow_tree(Xb, g, h, w, feat_mask, max_depth: int, n_bins: int,
              reg_lambda: float = 1.0, gamma: float = 0.0,
              min_child_weight: float = 1.0) -> Tree:
    """Grow one second-order histogram tree (traceable; static shapes).

    Xb: i32[n, d] pre-binned features; g: f32[n, c] gradients; h: f32[n]
    hessians; w: f32[n] row weights (bootstrap/balancing; 0 drops the row);
    feat_mask: f32[d] 1/0 feature subsampling mask.

    Gain (XGBoost): sum_c GL_c^2/(HL+l) + GR_c^2/(HR+l) - GT_c^2/(HT+l);
    leaf value: -G/(H+l).  With g=-y, h=1, l=0 this is exactly variance-gain
    splitting with mean leaves (Spark variance impurity), and with
    g=-onehot(y) it is gini-equivalent splitting with class-distribution
    leaves (Spark gini impurity).
    """
    n, d = Xb.shape
    c = g.shape[1]
    B = n_bins
    heap = 2 ** (max_depth + 1) - 1
    split_feat = jnp.full((heap,), -1, jnp.int32)
    split_bin = jnp.zeros((heap,), jnp.int32)
    leaf_val = jnp.zeros((heap, c), jnp.float32)
    node_ids = jnp.zeros((n,), jnp.int32)
    gw = g * w[:, None]
    hw = h * w

    for t in range(max_depth + 1):
        offset = 2 ** t - 1
        m = 2 ** t
        active = node_ids >= offset
        node_local = jnp.clip(node_ids - offset, 0, m - 1)
        G, H = _level_histograms(Xb, gw, hw, node_local, active, m, B)
        # node totals are identical across features; take feature 0's sums
        GT = G[:, 0].sum(axis=1)   # [m, c]
        HT = H[:, 0].sum(axis=1)   # [m]
        # leaf values for every active node at this level
        vals = -GT / (HT + reg_lambda)[:, None]      # [m, c]
        leaf_val = lax.dynamic_update_slice(leaf_val, vals, (offset, 0))
        if t == max_depth:
            break
        # split search: cumulative left stats over bins
        GL = jnp.cumsum(G, axis=2)                   # [m, d, B, c]
        HL = jnp.cumsum(H, axis=2)                   # [m, d, B]
        GR = GT[:, None, None, :] - GL
        HR = HT[:, None, None] - HL

        def score(Gp, Hp):
            return (Gp * Gp).sum(axis=-1) / (Hp + reg_lambda)

        gain = score(GL, HL) + score(GR, HR) - score(GT, HT)[:, None, None]  # [m,d,B]
        valid = (HL >= min_child_weight) & (HR >= min_child_weight)
        valid &= feat_mask[None, :, None] > 0.0
        valid &= jnp.arange(B)[None, None, :] < B - 1  # last bin: empty right
        gain = jnp.where(valid, gain, -jnp.inf)
        flat = gain.reshape(m, d * B)
        best = jnp.argmax(flat, axis=1)              # [m]
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        bf = (best // B).astype(jnp.int32)
        bb = (best % B).astype(jnp.int32)
        do_split = best_gain > gamma
        sf = jnp.where(do_split, bf, -1)
        split_feat = lax.dynamic_update_slice(split_feat, sf, (offset,))
        split_bin = lax.dynamic_update_slice(split_bin, bb, (offset,))
        # route rows: gather this node's split; stay put on leaves
        nf = split_feat[node_ids]                    # [n]
        nb = split_bin[node_ids]
        row_bin = jnp.take_along_axis(Xb, jnp.maximum(nf, 0)[:, None], axis=1)[:, 0]
        go_right = (row_bin > nb).astype(jnp.int32)
        child = 2 * node_ids + 1 + go_right
        node_ids = jnp.where((nf >= 0) & active, child, node_ids)
    return Tree(split_feat, split_bin, leaf_val)


def predict_tree(Xb, tree: Tree, max_depth: int) -> jax.Array:
    """f32[n, c] — walk the fixed-depth heap; rows rest at leaves."""
    n = Xb.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    for _ in range(max_depth):
        nf = tree.split_feat[node]
        nb = tree.split_bin[node]
        row_bin = jnp.take_along_axis(Xb, jnp.maximum(nf, 0)[:, None], axis=1)[:, 0]
        child = 2 * node + 1 + (row_bin > nb).astype(jnp.int32)
        node = jnp.where(nf >= 0, child, node)
    return tree.leaf_val[node]


# ---------------------------------------------------------------------------
# Random forest — vmap over trees
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("max_depth", "n_bins"))
def fit_forest(Xb, g, h, w_trees, feat_masks, max_depth: int, n_bins: int,
               reg_lambda: float = 1e-6, min_child_weight: float = 1.0) -> Tree:
    """Train all trees of a forest in one launch.

    w_trees: f32[T, n] bootstrap weights; feat_masks: f32[T, d].
    Returns Tree with leading tree axis.
    """

    def one(wt, fm):
        return grow_tree(Xb, g, h, wt, fm, max_depth, n_bins,
                         reg_lambda=reg_lambda, gamma=0.0,
                         min_child_weight=min_child_weight)

    return jax.vmap(one)(w_trees, feat_masks)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_forest(Xb, forest: Tree, max_depth: int) -> jax.Array:
    """Average the trees' leaf vectors: f32[n, c]."""
    preds = jax.vmap(lambda t: predict_tree(Xb, t, max_depth))(forest)  # [T, n, c]
    return preds.mean(axis=0)


def forest_chunk_size(max_depth: int, n_bins: int, d: int, c: int,
                      budget_bytes: float = 1.5e9) -> int:
    """Trees per chunk so one chunk's level histograms fit the budget.

    The deepest level materializes G [m, d, B, c] + H [m, d, B] per tree
    (m = 2^max_depth); deep trees would otherwise blow HBM when many train
    at once."""
    per_tree = (2 ** max_depth) * n_bins * d * (c + 1) * 4
    return max(1, int(budget_bytes / max(per_tree, 1)))


@functools.partial(jax.jit, static_argnames=("max_depth", "n_bins", "chunk"))
def fit_forest_chunked(Xb, g, h, w_trees, feat_masks, mcw_trees, max_depth: int,
                       n_bins: int, chunk: int, reg_lambda: float = 1e-6) -> Tree:
    """Train an arbitrary tree population with bounded memory: ``lax.map``
    over chunks of ``chunk`` vmapped trees — one compile, sequential chunks.

    The tree axis TT (a multiple of ``chunk``; callers pad with zero-weight
    trees) may interleave folds x grid candidates x bootstrap replicas —
    per-tree ``mcw_trees`` carries the grid's min-child-weight, so a whole
    RF fold x grid sweep is a single launch (SURVEY §2.7 axis 2).
    """
    n = Xb.shape[0]
    d = Xb.shape[1]

    def one_chunk(args):
        wts, fms, mcws = args

        def one(wt, fm, mcw):
            return grow_tree(Xb, g, h, wt, fm, max_depth, n_bins,
                             reg_lambda=reg_lambda, gamma=0.0,
                             min_child_weight=mcw)

        return jax.vmap(one)(wts, fms, mcws)

    trees = lax.map(one_chunk, (w_trees.reshape(-1, chunk, n),
                                feat_masks.reshape(-1, chunk, d),
                                mcw_trees.reshape(-1, chunk)))
    return jax.tree.map(lambda a: a.reshape((-1,) + a.shape[2:]), trees)


@functools.partial(jax.jit, static_argnames=("max_depth", "n_groups"))
def predict_forest_groups(Xb, forest: Tree, max_depth: int, n_groups: int) -> jax.Array:
    """Mean leaf vector per group of trees: f32[n_groups, n, c] — the eval
    half of the batched fold x grid RF sweep (forest axis = n_groups * T)."""
    preds = jax.vmap(lambda t: predict_tree(Xb, t, max_depth))(forest)  # [TT, n, c]
    return preds.reshape((n_groups, -1) + preds.shape[1:]).mean(axis=1)


# ---------------------------------------------------------------------------
# Gradient boosting — lax.scan over rounds
# ---------------------------------------------------------------------------
def _grad_hess(loss: str, F, y, Y_onehot):
    if loss == "squared":
        return (F[:, 0] - y)[:, None], jnp.ones_like(y)
    if loss == "logistic":
        p = jax.nn.sigmoid(F[:, 0])
        return (p - y)[:, None], jnp.maximum(p * (1 - p), 1e-6)
    if loss == "softmax":
        p = jax.nn.softmax(F, axis=-1)
        # scalar hessian approximation: mean over classes of p(1-p)
        return p - Y_onehot, jnp.maximum((p * (1 - p)).mean(axis=-1), 1e-6)
    raise ValueError(f"unknown loss {loss!r}")


def _gbt_impl(Xb, y, w, row_w_rounds, feat_mask_rounds, loss: str, n_rounds: int,
              max_depth: int, n_bins: int, eta, reg_lambda, gamma,
              min_child_weight, base_score: float, n_classes: int
              ) -> Tuple[Tree, jax.Array]:
    """Traceable boosting body shared by fit_gbt and fit_gbt_batch."""
    n = Xb.shape[0]
    c = n_classes if loss == "softmax" else 1
    Y = jax.nn.one_hot(y.astype(jnp.int32), max(c, 2), dtype=jnp.float32) \
        if loss == "softmax" else jnp.zeros((n, 2), jnp.float32)
    F0 = jnp.full((n, c), base_score, jnp.float32)

    def round_fn(F, xs):
        rw, fm = xs
        g, hh = _grad_hess(loss, F, y, Y)
        tree = grow_tree(Xb, g, hh, w * rw, fm, max_depth, n_bins,
                         reg_lambda=reg_lambda, gamma=gamma,
                         min_child_weight=min_child_weight)
        F = F + eta * predict_tree(Xb, tree, max_depth)
        return F, tree

    F, trees = lax.scan(round_fn, F0, (row_w_rounds, feat_mask_rounds))
    return trees, F


@functools.partial(jax.jit, static_argnames=("loss", "n_rounds", "max_depth",
                                             "n_bins", "n_classes"))
def fit_gbt(Xb, y, w, row_w_rounds, feat_mask_rounds, loss: str, n_rounds: int,
            max_depth: int, n_bins: int, eta: float = 0.3,
            reg_lambda: float = 1.0, gamma: float = 0.0,
            min_child_weight: float = 1.0, base_score: float = 0.0,
            n_classes: int = 1) -> Tuple[Tree, jax.Array]:
    """XGBoost-style boosting: scan over rounds, one histogram tree per round.

    row_w_rounds: f32[R, n] subsample weights per round; feat_mask_rounds:
    f32[R, d] colsample masks.  Multiclass uses multi-output trees (leaf
    vector per class) — a TPU-friendly variant of per-class tree sets.
    Returns (stacked Tree [R, ...], final margins F [n, c]).
    """
    return _gbt_impl(Xb, y, w, row_w_rounds, feat_mask_rounds, loss, n_rounds,
                     max_depth, n_bins, eta, reg_lambda, gamma, min_child_weight,
                     base_score, n_classes)


@functools.partial(jax.jit, static_argnames=("loss", "n_rounds", "max_depth",
                                             "n_bins", "n_classes"))
def fit_gbt_batch(Xb, y, w_batch, row_w_rounds, feat_mask_rounds, loss: str,
                  n_rounds: int, max_depth: int, n_bins: int,
                  eta_b, reg_lambda_b, gamma_b, min_child_weight_b,
                  base_score_b=None, n_classes: int = 1) -> jax.Array:
    """The fold x grid boosting sweep as ONE launch (the OpValidator
    thread-pool analog for boosted models — SURVEY §2.7 axis 2).

    ``w_batch`` f32[B, n] carries fold-mask x sample weights per batch
    element; ``eta_b``/``reg_lambda_b``/``gamma_b``/``min_child_weight_b``
    f32[B] are the grid's dynamic hyperparameters (static shape params —
    depth, rounds, bins — must match across the batch; the caller groups
    grids accordingly).  Returns final margins F f32[B, n, c] on the FULL
    dataset, from which fold-validation slices are taken.
    """

    if base_score_b is None:
        base_score_b = jnp.zeros(w_batch.shape[0], jnp.float32)

    def one(w, eta, lam, gam, mcw, base):
        _, F = _gbt_impl(Xb, y, w, row_w_rounds, feat_mask_rounds, loss,
                         n_rounds, max_depth, n_bins, eta, lam, gam, mcw,
                         base, n_classes)
        return F

    return jax.vmap(one)(w_batch, eta_b, reg_lambda_b, gamma_b,
                         min_child_weight_b, base_score_b)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_gbt(Xb, trees: Tree, max_depth: int, eta: float,
                base_score: float = 0.0) -> jax.Array:
    """Sum of shrunken tree outputs: f32[n, c]."""
    preds = jax.vmap(lambda t: predict_tree(Xb, t, max_depth))(trees)  # [R, n, c]
    return base_score + eta * preds.sum(axis=0)


# ---------------------------------------------------------------------------
# Host-side helpers for subsampling masks
# ---------------------------------------------------------------------------
def bootstrap_weights(n: int, n_trees: int, rng: np.random.Generator,
                      bootstrap: bool = True, rate: float = 1.0) -> np.ndarray:
    """Poisson(rate) bootstrap weights — the with-replacement limit Spark's
    BaggedPoint uses, with ``rate`` = RF subsamplingRate (each tree sees a
    bootstrap of expected size ``n * rate``)."""
    if not bootstrap:
        return np.ones((n_trees, n), np.float32)
    return rng.poisson(rate, size=(n_trees, n)).astype(np.float32)


def feature_masks(d: int, n_trees: int, frac: float,
                  rng: np.random.Generator) -> np.ndarray:
    """Per-tree feature-subset masks (featureSubsetStrategy / colsample)."""
    if frac >= 1.0:
        return np.ones((n_trees, d), np.float32)
    k = max(1, int(round(frac * d)))
    masks = np.zeros((n_trees, d), np.float32)
    for t in range(n_trees):
        masks[t, rng.choice(d, size=k, replace=False)] = 1.0
    return masks


def subsample_weights(n: int, n_rounds: int, frac: float,
                      rng: np.random.Generator) -> np.ndarray:
    """Per-round row-subsample masks (GBT subsamplingRate / XGB subsample)."""
    if frac >= 1.0:
        return np.ones((n_rounds, n), np.float32)
    return (rng.random((n_rounds, n)) < frac).astype(np.float32)
