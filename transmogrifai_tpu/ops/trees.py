"""Histogram-based decision-tree / forest / boosting kernels — pure XLA.

The reference gets trees from Spark MLlib (RandomForest/GBT/DecisionTree)
and the XGBoost C++ core over JNI (`build.gradle:90`,
core/.../impl/classification/OpXGBoostClassifier.scala:47).  On TPU the
idiomatic formulation is the *histogram method* with static shapes and no
per-row control flow (SURVEY §7 "Trees/GBT/XGBoost on TPU"):

- features are pre-quantized to ``n_bins`` integer bins (subsampled quantile
  sketch — XGBoost's approx sketch analog; Spark's maxBins),
- a tree grows breadth-first over a BOUNDED FRONTIER of ``M`` node slots:
  early levels are unrolled at their exact widths (1, 2, 4, ... nodes), deep
  levels run in ONE ``lax.fori_loop`` body with a fixed ``M``-slot frontier —
  so compile cost is independent of depth and per-level memory/compute is
  capped at ``M * d * B`` instead of ``2^depth * d * B``,
- per level the (slot, feature, bin) gradient histograms are built with
  ``segment_sum`` (one scatter per feature, vmapped) and the best split per
  slot is a pure cumsum/argmax reduction — all VPU/MXU-friendly,
- rows carry a frontier-slot id; the level update is a gather + compare,
- second-order (g, h) statistics make the same builder serve XGBoost-style
  boosting (Newton leaves), RF regression (g = -y: variance gain, mean
  leaves), and RF classification (g = -onehot(y): gini-equivalent gain,
  class-distribution leaves),
- a forest is ``vmap(grow_tree)`` over bootstrap row-weights and feature
  masks; boosting is ``lax.scan`` over rounds — a whole RF trains as ONE
  XLA launch and boosting compiles to a single fixed-trip loop.

Frontier exactness: depth-wise growth is EXACT whenever every level has at
most ``M // 2`` valid splits.  A valid split needs hessian weight
``>= min_child_weight`` in each child, so at most ``H_total / (2 * mcw)``
nodes per level can split — ``frontier_cap`` sizes ``M`` from that bound.
When data is so large that the bound exceeds ``max_frontier``, growth becomes
a gain-ranked beam (LightGBM max-leaves analog) — the standard bounded-width
compromise, documented here rather than hidden.

Trees are stored as flat pointer arrays: ``split_feat`` (-1 = leaf),
``split_bin``, ``left``/``right`` child pool indices, ``leaf_val[pool, c]``
— pytree-friendly and trivially serializable.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.mesh import mesh_psum, record_trace_event


class Tree(NamedTuple):
    """One tree as a flat node pool; leading axes may batch trees/rounds."""

    split_feat: jax.Array  # i32[P]  (-1 => leaf)
    split_bin: jax.Array   # i32[P]  (go right if bin > split_bin)
    left: jax.Array        # i32[P]  pool index of left child
    right: jax.Array       # i32[P]  pool index of right child
    leaf_val: jax.Array    # f32[P, c]


# ---------------------------------------------------------------------------
# Quantization — subsampled quantile sketch (XGBoost approx / Spark maxBins)
# ---------------------------------------------------------------------------
_SKETCH_ROWS = 1 << 18  # 262144 — plenty for <=256 quantile edges


def _bin_dtype(n_bins: int):
    """Narrowest dtype holding every bin id in [0, n_bins).

    int8 tops out at +127, so it is safe through ``n_bins == 128`` (ids
    0..127) and must promote to int32 beyond — at exactly 128 the old
    ``<= 127`` boundary promoted a bin matrix that still fit, and one bin
    more would have overflowed int8 had the comparison been ``< 256``-style
    sloppy.  Regression-pinned at 127/128/255/256 in
    tests/test_trees_binning.py."""
    if n_bins < 2:
        raise ValueError(f"n_bins must be >= 2 (one split edge), got {n_bins}")
    return np.int8 if n_bins <= 128 else np.int32


@jax.jit
def _bin_chunk(X, edges):
    """i32[n, d]: per-feature searchsorted (left) — log2(B) compare steps."""
    return jax.vmap(lambda e, x: jnp.searchsorted(e, x, side="left"),
                    in_axes=(0, 1), out_axes=1)(edges, X)


def sketch_edges(X: np.ndarray, n_bins: int, seed: int = 0) -> np.ndarray:
    """Quantile split candidates f32[d, n_bins-1] from a row subsample."""
    X = np.asarray(X, np.float32)
    n = X.shape[0]
    if n > _SKETCH_ROWS:
        idx = np.random.default_rng(seed).choice(n, _SKETCH_ROWS, replace=False)
        X = X[idx]
    qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return np.quantile(X, qs, axis=0).T.astype(np.float32)  # [d, n_bins-1]


def bin_with_edges(X: np.ndarray, edges: np.ndarray,
                   chunk: int = 1 << 20) -> np.ndarray:
    """Apply fitted edges (vectorized on device, row-chunked for huge n).

    Bin b holds values in (edges[b-1], edges[b]]; value <= edges[0] is bin 0;
    value > edges[-1] is the last bin.
    """
    X = np.asarray(X, np.float32)
    n = X.shape[0]
    n_bins = edges.shape[1] + 1
    dt = _bin_dtype(n_bins)
    ed = jnp.asarray(edges)
    if n <= chunk:
        return np.asarray(_bin_chunk(jnp.asarray(X), ed)).astype(dt)
    out = np.empty(X.shape, dt)
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        out[lo:hi] = np.asarray(_bin_chunk(jnp.asarray(X[lo:hi]), ed)).astype(dt)
    return out


def quantize(X: np.ndarray, n_bins: int = 32,
             seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Equi-depth binning: (X_binned int8/i32[n, d], edges f32[d, n_bins-1])."""
    edges = sketch_edges(X, n_bins, seed=seed)
    return bin_with_edges(X, edges), edges


# ---------------------------------------------------------------------------
# Frontier sizing
# ---------------------------------------------------------------------------
def _next_pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 1).bit_length()


def frontier_cap(n: int, max_depth: int, min_child_weight: float = 1.0,
                 h_max: float = 1.0, max_frontier: int = 512,
                 total_weight: float = None) -> int:
    """Frontier slots M for ``grow_tree`` (static; power of two).

    At most ``H_total / (2 * mcw)`` nodes can validly split per level
    (children need hessian weight >= mcw each), so a frontier of
    ``H_total / mcw`` slots loses nothing.  ``h_max`` bounds one row's
    hessian per unit weight (1 for variance/gini trees, 0.25 for
    logistic/softmax).  ``total_weight`` is the actual row-weight sum (max
    over the tree batch) — callers that know their weights (bootstrap,
    DataBalancer up-weighting) MUST pass it; the 1.25*n fallback only covers
    unweighted rows plus mild Poisson-bootstrap inflation.  Beyond
    ``max_frontier`` growth is a gain-ranked beam (see module docstring).
    """
    if max_depth <= 1:
        return 2
    tw = 1.25 * n if total_weight is None else float(total_weight)
    exact = int(np.ceil(h_max * tw / max(min_child_weight, 1e-3)))
    # 2^max_depth (not 2^(max_depth-1)): the last split level's children must
    # all fit the next frontier, else the beam silently halves the deepest
    # level; when this term binds the tree is fully unrolled and exact.
    m = min(1 << max_depth, max(exact, 2), max_frontier, _next_pow2(n))
    return max(_next_pow2(m) if m & (m - 1) else m, 2)


def _pool_size(max_depth: int, frontier: int) -> int:
    """Node-pool capacity: exact heap for unrolled levels + M per loop level.

    Pool layout is STATIC: level t < log2(M) occupies [2^t - 1, 2^(t+1) - 1);
    loop level t >= log2(M) occupies [M - 1 + (t - L)*M, ...+M).  Every level
    claims its full block whether or not all slots split — offsets are then
    independent of the tree, so the batched node/leaf writes stay single
    vectorized ops under vmap instead of serializing per tree.
    """
    if max_depth <= 0:
        return 1
    L = frontier.bit_length() - 1  # log2(M)
    u = min(max_depth, L)
    return (1 << (u + 1)) - 1 + max(max_depth - L, 0) * frontier


def frontier_is_exact(n: int, max_depth: int, min_child_weight: float,
                      h_max: float, frontier: int,
                      total_weight: float = None) -> bool:
    """True when ``frontier`` provably cannot overflow (no beam truncation):
    a level's children are bounded by H_total / mcw <= h_max*sum(w) / mcw,
    so a frontier at least that wide (or fully unrolled) never ranks splits.
    The exact-cap fast path then replaces the gain-rank argsorts with a
    trivial count clamp.  ``total_weight`` must be the ACTUAL max weight sum
    over the tree batch when weights can exceed 1 per row (Poisson
    bootstrap, DataBalancer ~n/(1-p)); the 1.25*n fallback is only safe for
    near-unit weights."""
    tw = 1.25 * n if total_weight is None else float(total_weight)
    exact = int(np.ceil(h_max * tw / max(min_child_weight, 1e-3)))
    return frontier >= min(1 << max_depth, exact)


# ---------------------------------------------------------------------------
# Tree growth
# ---------------------------------------------------------------------------
def _hist_bf16() -> bool:
    """bf16 inputs for the histogram matmul (f32 accumulation).

    Exact for RF (one-hot entries, 0/-1 gradients and small-int bootstrap
    weights are all bf16-representable); boosted gradients round to ~3
    decimal digits, which only perturbs near-tie split choices.
    TMOG_HIST_BF16=0/1 forces either way (parity tests force 0).
    """
    import os

    force = os.environ.get("TMOG_HIST_BF16")
    if force is not None and force != "":
        return force == "1"
    # measured on v5e: bf16 inputs LOSE ~2x on this matmul shape (the convert
    # + re-layout outweighs the MXU saving at these small contractions)
    return False


def _hist_subtract() -> bool:
    """Parent-minus-child histogram subtraction (the XGBoost/LightGBM trick).

    Each split level builds per-bin G/H histograms only for the LIGHTER
    child (by hessian weight) of every sibling pair and derives the heavy
    sibling as ``parent_hist - light_hist`` from parent histograms carried
    level to level — halving the dominant histogram-build cost and, on
    row-sharded launches, the psum payload (the subtraction happens AFTER
    the data-axis psum on already-global stats).  Not bitwise-identical to
    the direct build (f32 ``parent - light`` rounds differently than
    summing the heavy rows), so near-tied splits can flip; parity is pinned
    at the sweep-metric level in tests/test_hist_subtract_parity.py.
    TMOG_HIST_SUBTRACT=0/1 forces either way (default on).
    """
    import os

    force = os.environ.get("TMOG_HIST_SUBTRACT")
    if force is not None and force != "":
        return force == "1"
    return True


def _hist_via_matmul(n: int, d: int, n_bins: int, c1: int = 2) -> bool:
    """Pick the histogram formulation (static, at trace time).

    TPU: scatters (segment_sum) serialize on the VPU and dominated the
    round-2 sweep; the one-hot-matmul formulation routes the same reduction
    through the MXU (measured ~20x faster on the Titanic sweep despite doing
    more raw FLOPs).  It materializes a shared [n, c1*d*B] gradient one-hot,
    so fall back to segment_sum when that exceeds ~2 GB (the 10M x 500 scale
    config row-shards first, keeping each shard under the cap).  CPU keeps
    segment_sum — scalar scatters are cheap there and the one-hot is pure
    overhead.  TMOG_HIST_MATMUL=0/1 forces either path (parity tests).
    """
    import os

    force = os.environ.get("TMOG_HIST_MATMUL")
    if force is not None and force != "":
        return force == "1"
    if jax.default_backend() != "tpu":
        return False
    return float(n) * d * n_bins * c1 * (2 if _hist_bf16() else 4) <= 2e9


def _bf16_hist_acc() -> bool:
    """bf16 G/H histogram ACCUMULATION (``TMOG_BF16_HIST``, default off).

    Distinct from ``_hist_bf16`` (TMOG_HIST_BF16), which casts the matmul
    INPUTS to bf16 while still accumulating in f32: this knob makes the
    accumulator itself bf16 (``preferred_element_type=bfloat16`` on the
    level GEMMs / bf16 ``segment_sum``), halving the histogram HBM traffic
    — the dominant memory stream of a level build.  Histograms are cast
    back to f32 IMMEDIATELY after the build, before the data-axis psum and
    all split-gain arithmetic, so cross-device reductions and gain math
    stay f32; only the per-bin accumulation rounds (~8-bit mantissa).
    Split choices can flip on near-ties; sweep-metric parity is pinned in
    tests/test_sweep_pack.py.  Each level build emits a ``bf16_hist``
    trace event carrying the bytes saved vs f32 (utils/flops bucket).
    """
    from ..utils.env import env_flag

    return env_flag("TMOG_BF16_HIST", False)


def bin_onehot(Xb, n_bins: int) -> jax.Array:
    """Gradient-FREE histogram RHS: [n, d*B] with entry (r, j*B + b) =
    1[bin(r, j) == b].  Depends only on the binned matrix, so boosting
    builds it ONCE per launch (the gradient-carrying ``grad_onehot`` must be
    rebuilt every round); per-tree gradients then ride the LHS of the level
    GEMM (see ``_grow_level_batch``'s gh_t path).  Honors the same
    ``_hist_bf16`` knob as ``grad_onehot`` (0/1 entries are bf16-exact)."""
    n, d = Xb.shape
    dt = jnp.bfloat16 if _hist_bf16() else jnp.float32
    oh = jax.nn.one_hot(Xb.astype(jnp.int32), n_bins, dtype=dt)
    return oh.reshape(n, -1)


def grad_onehot(Xb, gh, n_bins: int) -> jax.Array:
    """Shared RHS of the level-histogram matmul: [n, c1*d*B] where entry
    (r, c*d*B + j*B + b) = gh[r, c] * 1[bin(r, j) == b].

    Built ONCE per launch (gradients are constant across a forest's levels;
    per boosting round for GBT) and contracted against the per-level
    weighted slot one-hot — row weights live on the slot side, so this
    tensor is shared by every tree of a vmapped forest."""
    n, d = Xb.shape
    dt = jnp.bfloat16 if _hist_bf16() else jnp.float32
    oh = jax.nn.one_hot(Xb.astype(jnp.int32), n_bins, dtype=dt)  # [n, d, B]
    og = gh.astype(dt)[:, :, None, None] * oh[:, None, :, :]     # [n, c1, d, B]
    return og.reshape(n, -1)


def _level_histograms_mm(Og, S, w, m: int, n_bins: int, d: int, c1: int):
    """MXU histogram build: G [m, c, d, B], H [m, d, B] via ONE matmul.

    S = one_hot(row_slot) [n, m] (slot -1 -> all-zero row, i.e. resting
    rows drop out); row weights fold into S here so ``Og`` stays shared;
    GH = (S*w)^T @ Og — a single [m, n] x [n, c1*d*B] contraction instead
    of d scatters.  Accumulation is always f32 (preferred_element_type);
    the bins axis stays minor so no tensor has a 2-wide lane dimension.
    """
    Sw = S * w.astype(S.dtype)[:, None]
    acc_dt = jnp.bfloat16 if _bf16_hist_acc() else jnp.float32
    if acc_dt == jnp.bfloat16:
        record_trace_event("bf16_hist", "mm", 2 * m * c1 * d * n_bins)
    GH = lax.dot_general(Sw.astype(Og.dtype), Og, (((0,), (0,)), ((), ())),
                         preferred_element_type=acc_dt)          # [m, c1*d*B]
    GH = GH.astype(jnp.float32).reshape(m, c1, d, n_bins)
    return GH[:, :c1 - 1], GH[:, c1 - 1]


def _level_histograms(Xb, ghw, row_slot, m: int, n_bins: int):
    """Per-(slot, feature, bin) stats: G [m, c, d, B], H [m, d, B].

    ghw: f32[n, c+1] — weighted gradients with the weighted hessian as the
    last channel, so G and H come out of ONE scatter per feature.
    row_slot: i32[n] in [0, m) or -1 (resting at a leaf -> overflow bucket).
    """
    B = n_bins
    d = Xb.shape[1]
    dead = row_slot < 0
    base = jnp.where(dead, m * B, row_slot * B)
    if _bf16_hist_acc():
        record_trace_event("bf16_hist", "segment",
                           2 * m * ghw.shape[1] * d * B)
        ghw = ghw.astype(jnp.bfloat16)

    def per_feature(bins_j):
        seg = base + jnp.where(dead, 0, bins_j)
        return jax.ops.segment_sum(ghw, seg, num_segments=m * B + 1)[:-1]

    GH = jax.vmap(per_feature, in_axes=1,
                  out_axes=0)(Xb).astype(jnp.float32)      # [d, m*B, c+1]
    c = ghw.shape[1] - 1
    GH = GH.reshape(d, m, B, c + 1).transpose(1, 3, 0, 2)  # [m, c1, d, B]
    return GH[:, :c], GH[:, c]


def _grow_level(Xb, gh, w, feat_mask, nodes, leaf_val, slot_base, next_free,
                n_active, row_slot, row_node, m: int, next_cap: int,
                n_bins: int, reg_lambda, gamma, min_child_weight,
                min_info_gain=0.0, Og=None, exact_cap: bool = False,
                axis_name: Optional[str] = None, pair_light=None,
                pair_hist=None, want_pairs: bool = False):
    """One breadth-first level over an ``m``-slot frontier.

    SCATTER/GATHER-FREE by design: XLA TPU lowers batched scatters and
    per-element gathers to near-serial loops (~10 ms per level at 900 trees
    x 891 rows, measured), so every per-row lookup of per-slot data rides an
    MXU matmul against the slot one-hot ``S``, node records land with ONE
    ``dynamic_update_slice`` per level (the frontier occupies the static
    pool block ``[slot_base, slot_base + m)`` — see ``_pool_size``; offsets
    are tree-independent so the batched write stays one vectorized op),
    children pack into ``[next_free, next_free + 2k)`` via tiny selection
    matmuls (no argsort), and the next frontier needs no materialized map —
    slot j of the next level IS pool id ``next_free + j``.

    ``nodes`` is the packed i32[P, 4] pool (feat, bin, left, right);
    ``leaf_val`` f32[P, c]; ``n_active`` the live width of the frontier
    (slots beyond it are dead); ``slot_base``/``next_free`` are scalars
    uniform across a vmapped batch (python ints or loop-index affine).
    Returns (nodes', leaf_val', n_active', row_slot', row_node').  ``m`` and
    ``next_cap`` are static; when ``next_cap < 2*m`` the level keeps only
    the top ``next_cap // 2`` splits by gain — unless ``exact_cap`` says the
    frontier provably cannot overflow, where a count clamp replaces the
    sorts.  ``Og`` (shared gradient one-hot) selects the MXU matmul
    histogram build.  A node's leaf value is written once, when the node is
    created (root at init).  ``row_node`` tracks each row's current pool
    node so boosting can read final leaf values without a predict walk.

    Histogram subtraction (``_hist_subtract``): with ``pair_hist``
    f32[m/2, c+1, d, B] (the parent slots' histograms, packed at sibling-
    pair positions by the PREVIOUS level) and ``pair_light`` f32[m/2]
    (1.0 = the lighter child sits in the even/left slot), histograms are
    built only for the light child of each pair; the heavy sibling is
    ``parent - light`` AFTER the data-axis psum.  ``want_pairs`` appends
    (pair_light', pair_hist') for the NEXT level to the return tuple.
    """
    B = n_bins
    d = Xb.shape[1]
    c = gh.shape[1] - 1
    iota_m = jnp.arange(m)
    in_use = iota_m < n_active
    subtract = pair_hist is not None
    pairs = m // 2
    if Og is not None:
        S = jax.nn.one_hot(row_slot, m, dtype=jnp.float32)       # [n, m]
        if subtract:
            # light-child membership from the full slot one-hot: select the
            # light column of each sibling pair (no gathers)
            light_sel = jnp.stack([pair_light, 1.0 - pair_light], axis=-1)
            S_light = (S.reshape(-1, pairs, 2) * light_sel[None]).sum(-1)
            record_trace_event("hist_subtracted", "mm",
                               2 * pairs * S.shape[0] * (c + 1) * d * B)
            Gl, Hl = _level_histograms_mm(Og, S_light, w, pairs, B, d, c + 1)
        else:
            G, H = _level_histograms_mm(Og, S, w, m, B, d, c + 1)
    else:
        S = None
        if subtract:
            # CPU segment-sum path: gathers are cheap here, so route light
            # rows straight to their pair id and rest everything else
            lp_slot = pair_light > 0.5
            light_slot = jnp.stack([lp_slot, ~lp_slot], axis=-1).reshape(-1)
            s_safe = jnp.maximum(row_slot, 0)
            is_light = light_slot[s_safe] & (row_slot >= 0)
            pair_ids = jnp.where(is_light, row_slot >> 1, -1)
            record_trace_event("hist_subtracted", "segment",
                               row_slot.shape[0] * (c + 1) * d // 2)
            Gl, Hl = _level_histograms(Xb, gh * w[:, None], pair_ids, pairs, B)
        else:
            G, H = _level_histograms(Xb, gh * w[:, None], row_slot, m, B)
    # row-sharded launch: local-rows histograms psum to the GLOBAL per-bin
    # stats, so every shard picks identical splits (distributed-XGBoost
    # histogram aggregation); row routing below stays local.  On the
    # subtracted path only the LIGHT histograms cross the wire (half the
    # payload); parents are already post-psum globals from the prior level.
    if subtract:
        Gl = mesh_psum(Gl, axis_name)                # [pairs, c, d, B]
        Hl = mesh_psum(Hl, axis_name)                # [pairs, d, B]
        Gh = pair_hist[:, :c] - Gl
        Hh = pair_hist[:, c] - Hl
        lp = pair_light > 0.5                        # light child is LEFT
        lpg = lp[:, None, None, None]
        lph = lp[:, None, None]
        G = jnp.stack([jnp.where(lpg, Gl, Gh),
                       jnp.where(lpg, Gh, Gl)], axis=1).reshape(m, c, d, B)
        H = jnp.stack([jnp.where(lph, Hl, Hh),
                       jnp.where(lph, Hh, Hl)], axis=1).reshape(m, d, B)
    else:
        G = mesh_psum(G, axis_name)
        H = mesh_psum(H, axis_name)
    # G: [m, c, d, B]; H: [m, d, B] — bins minor, no 2-wide lane dims
    GT = G[:, :, 0, :].sum(axis=-1)   # [m, c] — node totals (same per feature)
    HT = H[:, 0, :].sum(axis=-1)      # [m]

    GL = jnp.cumsum(G, axis=-1)                  # [m, c, d, B]
    HL = jnp.cumsum(H, axis=-1)                  # [m, d, B]
    GR = GT[:, :, None, None] - GL
    HR = HT[:, None, None] - HL

    def score(Gp, Hp):
        return (Gp * Gp).sum(axis=1) / (Hp + reg_lambda)

    gain = score(GL, HL) + score(GR, HR) - score(GT, HT)[:, None, None]  # [m,d,B]
    valid = (HL >= min_child_weight) & (HR >= min_child_weight)
    valid &= feat_mask[None, :, None] > 0.0
    valid &= jnp.arange(B)[None, None, :] < B - 1  # last bin: empty right side
    gain = jnp.where(valid, gain, -jnp.inf)
    flat = gain.reshape(m, d * B)
    best = jnp.argmax(flat, axis=1)              # [m]
    best_gain = jnp.max(flat, axis=1)
    bf = (best // B).astype(jnp.int32)
    bb = (best % B).astype(jnp.int32)
    # Spark minInfoGain parity: our gain is the total-sum-of-squares drop,
    # which equals node_weight * Spark's per-row impurity decrease for both
    # gini (g=-onehot) and variance (g=-y) trees — so the per-row threshold
    # scales by the node's hessian total (DefaultSelectorParams.MinInfoGain).
    do_split = (best_gain > gamma) & (best_gain >= min_info_gain * HT) & in_use
    half = next_cap // 2
    if next_cap < 2 * m and not exact_cap:
        # beam cap: keep top half splits by gain (scatter-free inverse perm)
        key = jnp.where(do_split, -best_gain, jnp.inf)
        rank = jnp.argsort(jnp.argsort(key))
        do_split &= rank < half
        k = jnp.cumsum(do_split.astype(jnp.int32))
    else:
        k = jnp.cumsum(do_split.astype(jnp.int32))
        if next_cap < 2 * m:  # provably non-binding; clamp guards anyway
            do_split &= k <= half
            k = jnp.minimum(k, half)
    n_split = k[-1]
    child_idx = (k - 1) * 2                      # left child's next-level slot
    left_pool = next_free + child_idx
    right_pool = left_pool + 1
    # node records for the whole frontier, ONE dynamic_update_slice.  Slots
    # past the live frontier get the leaf default — which is exactly the
    # correct initial record for the children this level allocates there.
    rec = jnp.stack([jnp.where(do_split, bf, -1),
                     jnp.where(do_split, bb, 0),
                     jnp.where(do_split, left_pool, 0),
                     jnp.where(do_split, right_pool, 0)], axis=-1)   # [m, 4]
    nodes = lax.dynamic_update_slice(nodes, rec, (slot_base, 0))
    # children's leaf values straight from the winning split's stats; the
    # best-split slice is a one-hot reduction, not a take_along_axis gather
    onehot_best = jax.nn.one_hot(best, d * B, dtype=GL.dtype)        # [m, dB]
    GL_best = (GL.reshape(m, c, d * B) * onehot_best[:, None, :]).sum(-1)
    HL_best = (HL.reshape(m, d * B) * onehot_best).sum(-1)
    GR_best = GT - GL_best
    HR_best = HT - HL_best
    # dead slots have HL_best = 0; with reg_lambda = 0 the ratio is 0/0 = NaN
    # and 0 * NaN would poison the child-packing matmul below — zero them
    lval = jnp.where(do_split[:, None],
                     -GL_best / (HL_best + reg_lambda)[:, None], 0.0)
    rval = jnp.where(do_split[:, None],
                     -GR_best / (HR_best + reg_lambda)[:, None], 0.0)
    # pack (lval, rval) of the k split slots into the contiguous child block
    # [next_free, next_free + 2k) with two tiny selection matmuls (slot s's
    # left child lands at position child_idx[s], right at +1); the tail
    # beyond 2k stays zero in not-yet-allocated pool slots, which later
    # levels overwrite or leave unreachable (no pointer ever reaches them)
    iota_cap = jnp.arange(next_cap)
    pos_l = jnp.where(do_split, child_idx, -1)
    pos_r = jnp.where(do_split, child_idx + 1, -1)
    L_eq = (iota_cap[:, None] == pos_l[None, :]).astype(leaf_val.dtype)
    R_eq = (iota_cap[:, None] == pos_r[None, :]).astype(leaf_val.dtype)
    child_vals = L_eq @ lval + R_eq @ rval                   # [next_cap, c]
    leaf_val = lax.dynamic_update_slice(leaf_val, child_vals, (next_free, 0))
    # route rows: each row needs its slot's (do_split, bb, child_idx, bf);
    # gather-via-matmul against S — per-element gathers serialize on TPU
    if S is not None:
        pack = jnp.concatenate(
            [do_split.astype(jnp.float32)[:, None],
             bb.astype(jnp.float32)[:, None],
             child_idx.astype(jnp.float32)[:, None],
             jax.nn.one_hot(bf, d, dtype=jnp.float32)], axis=1)      # [m, 3+d]
        routed = S @ pack                                            # [n, 3+d]
        splits_here = routed[:, 0] > 0.5
        child_r = routed[:, 2].astype(jnp.int32)
        row_bin = (routed[:, 3:] * Xb).sum(axis=1)   # f32-exact small ints
        go_right = (row_bin > routed[:, 1]).astype(jnp.int32)
    else:
        s_safe = jnp.maximum(row_slot, 0)
        splits_here = do_split[s_safe] & (row_slot >= 0)
        row_bin = jnp.take_along_axis(Xb, bf[s_safe][:, None], axis=1)[:, 0]
        go_right = (row_bin > bb[s_safe]).astype(jnp.int32)
        child_r = child_idx[s_safe]
    new_row_slot = jnp.where(splits_here, child_r + go_right, -1)
    row_node = jnp.where(splits_here, next_free + child_r + go_right, row_node)
    if want_pairs:
        # parent histograms for the NEXT level's sibling pairs: slot s's
        # (post-psum, post-reassembly) G/H packed at pair j = child_idx/2 by
        # reusing every other row of the child-packing selector L_eq; the
        # light-left flag comes from the winning split's child hessians
        GH_all = jnp.concatenate([G, H[:, None]], axis=1).reshape(m, -1)
        P_pair = L_eq[0::2]                          # [next_cap // 2, m]
        new_pair_hist = (P_pair @ GH_all).reshape(next_cap // 2, c + 1, d, B)
        new_pair_light = P_pair @ (HL_best <= HR_best).astype(jnp.float32)
        return (nodes, leaf_val, 2 * n_split, new_row_slot, row_node,
                new_pair_light, new_pair_hist)
    return nodes, leaf_val, 2 * n_split, new_row_slot, row_node


def grow_tree(Xb, g, h, w, feat_mask, max_depth: int, n_bins: int,
              frontier: int, reg_lambda: float = 1.0, gamma: float = 0.0,
              min_child_weight: float = 1.0, min_info_gain=0.0,
              Og=None, return_row_node: bool = False,
              exact_cap: bool = False, axis_name: Optional[str] = None):
    """Grow one second-order histogram tree (traceable; static shapes).

    Xb: int[n, d] pre-binned features; g: f32[n, c] gradients; h: f32[n]
    hessians; w: f32[n] row weights (bootstrap/balancing; 0 drops the row);
    feat_mask: f32[d] 1/0 feature subsampling mask; ``frontier``: static
    frontier width M (see ``frontier_cap``); ``Og``: optional shared
    ``grad_onehot(Xb, concat([g, h], 1), n_bins)`` selecting the MXU
    histogram path.  With ``return_row_node`` the final (tree, row_node)
    pair is returned — ``leaf_val[row_node]`` is the tree's prediction on
    the training rows, sparing boosting a predict walk.

    Gain (XGBoost): sum_c GL_c^2/(HL+l) + GR_c^2/(HR+l) - GT_c^2/(HT+l);
    leaf value: -G/(H+l).  With g=-y, h=1, l~0 this is exactly variance-gain
    splitting with mean leaves (Spark variance impurity), and with
    g=-onehot(y) it is gini-equivalent gain with class-distribution leaves
    (Spark gini impurity).
    """
    Xb = Xb.astype(jnp.int32)
    n, d = Xb.shape
    c = g.shape[1]
    P = _pool_size(max_depth, frontier)
    gw = g * w[:, None]
    hw = h * w
    root_val = (-mesh_psum(gw.sum(axis=0), axis_name)
                / (mesh_psum(hw.sum(), axis_name) + reg_lambda))  # [c]
    nodes = jnp.tile(jnp.asarray([-1, 0, 0, 0], jnp.int32), (P, 1))
    leaf_val = jnp.zeros((P, c), jnp.float32).at[0].set(root_val)
    row_node = jnp.zeros((n,), jnp.int32)

    def as_tree(nodes, leaf_val):
        return Tree(split_feat=nodes[:, 0], split_bin=nodes[:, 1],
                    left=nodes[:, 2], right=nodes[:, 3], leaf_val=leaf_val)

    if max_depth <= 0:  # single leaf
        tree = as_tree(nodes, leaf_val)
        return (tree, row_node) if return_row_node else tree
    gh = jnp.concatenate([g, h[:, None]], axis=1)  # unweighted; w rides S

    M = frontier
    L = M.bit_length() - 1
    # histogram subtraction only pays from level 1 on (the root has no
    # sibling); the pair carry rides alongside the 5-tuple when enabled
    sub = _hist_subtract() and max_depth > 1
    carry = (nodes, leaf_val,
             jnp.asarray(1, jnp.int32),          # n_active (just the root)
             jnp.zeros((n,), jnp.int32),         # row_slot
             row_node)
    pl = ph = None
    # exact unrolled levels: widths 1, 2, 4, ..., min(2^(depth-1), M/ --)
    # static pool layout (_pool_size): level t's frontier block starts at
    # 2^t - 1; loop level t's at M - 1 + (t - L)*M — uniform across trees
    u = min(max_depth, L)
    for t in range(u):
        next_cap = 1 << (t + 1)                  # = 2m: no beam cap
        out = _grow_level(
            Xb, gh, w, feat_mask, carry[0], carry[1], (1 << t) - 1,
            (1 << (t + 1)) - 1, *carry[2:], m=1 << t, next_cap=next_cap,
            n_bins=n_bins, reg_lambda=reg_lambda, gamma=gamma,
            min_child_weight=min_child_weight, min_info_gain=min_info_gain,
            Og=Og, exact_cap=exact_cap, axis_name=axis_name,
            pair_light=pl, pair_hist=ph, want_pairs=sub)
        if sub:
            carry, pl, ph = out[:5], out[5], out[6]
        else:
            carry = out
    # deep levels: ONE fori_loop body at fixed M slots.  With subtraction
    # the carry gains (pair_light [M/2], pair_hist [M/2, c+1, d, B]) — the
    # last unrolled level's next_cap is exactly M, so the shapes are static
    # across iterations.
    if max_depth > L:
        if sub:
            def body(t, state):
                sb = M - 1 + (t - L) * M         # affine in t: batch-uniform
                return _grow_level(
                    Xb, gh, w, feat_mask, state[0], state[1], sb, sb + M,
                    *state[2:5], m=M, next_cap=M, n_bins=n_bins,
                    reg_lambda=reg_lambda, gamma=gamma,
                    min_child_weight=min_child_weight,
                    min_info_gain=min_info_gain, Og=Og, exact_cap=exact_cap,
                    axis_name=axis_name, pair_light=state[5],
                    pair_hist=state[6], want_pairs=True)

            carry = lax.fori_loop(L, max_depth, body,
                                  tuple(carry) + (pl, ph))[:5]
        else:
            def body(t, carry):
                sb = M - 1 + (t - L) * M         # affine in t: batch-uniform
                return _grow_level(Xb, gh, w, feat_mask, carry[0], carry[1],
                                   sb, sb + M, *carry[2:], m=M, next_cap=M,
                                   n_bins=n_bins, reg_lambda=reg_lambda,
                                   gamma=gamma,
                                   min_child_weight=min_child_weight,
                                   min_info_gain=min_info_gain, Og=Og,
                                   exact_cap=exact_cap, axis_name=axis_name)

            carry = lax.fori_loop(L, max_depth, body, carry)
    nodes, leaf_val, row_node = carry[0], carry[1], carry[4]
    tree = as_tree(nodes, leaf_val)
    return (tree, row_node) if return_row_node else tree


def predict_tree(Xb, tree: Tree, max_depth: int) -> jax.Array:
    """f32[n, c] — pointer walk for ``max_depth`` steps; rows rest at leaves."""
    Xb = Xb.astype(jnp.int32)
    n = Xb.shape[0]
    node0 = jnp.zeros((n,), jnp.int32)

    def step(_, node):
        nf = tree.split_feat[node]
        nb = tree.split_bin[node]
        row_bin = jnp.take_along_axis(Xb, jnp.maximum(nf, 0)[:, None], axis=1)[:, 0]
        child = jnp.where(row_bin > nb, tree.right[node], tree.left[node])
        return jnp.where(nf >= 0, child, node)

    node = lax.fori_loop(0, max_depth, step, node0) if max_depth > 0 else node0
    return tree.leaf_val[node]


# ---------------------------------------------------------------------------
# Batch-native level grower — the whole tree chunk in ONE flat GEMM per level
#
# Round-5 measurement (tools/probe_hist_mm.py, v5e): the vmapped per-tree
# histogram contraction ([m, n] @ [n, c1*d*B] batched over ~600 trees) runs
# at ~2 TFLOP/s, while the SAME reduction flattened to a single
# [T*m, n] @ [n, c1*d*B] GEMM runs at ~28 TFLOP/s — XLA lowers the big-M
# 2-D GEMM onto the MXU 14x better than the small-M batched-GEMM.  So the
# forest kernels grow their whole chunk with an explicit tree axis: slot
# one-hots are built [T, m, n] (slot axis ahead of rows: no transpose before
# the flatten) and every level runs one flat GEMM.
# ---------------------------------------------------------------------------
def _grow_level_batch(Xb, gh, w_t, feat_mask_t, nodes, leaf_val, slot_base,
                      next_free, n_active, row_slot, row_node, m: int,
                      next_cap: int, n_bins: int, reg_lambda_t, gamma_t,
                      mcw_t, mig_t, Og, exact_cap: bool,
                      gh_t=None, Obin=None, axis_name: Optional[str] = None,
                      pair_light=None, pair_hist=None,
                      want_pairs: bool = False):
    """One breadth-first level for a BATCH of T trees (shared Xb).

    Same split math as ``_grow_level`` (see its docstring for the
    scatter/gather-free design); shapes carry a leading tree axis:
    w_t f32[T, n], feat_mask_t f32[T, d], nodes i32[T, P, 4],
    leaf_val f32[T, P, c], n_active i32[T], row_slot/row_node i32[T, n],
    per-tree hyperparameters f32[T].  Two GEMM layouts:

    - SHARED gradients (forests: every tree of the sweep sees the same
      g/h): ``gh`` f32[n, c1] + ``Og = grad_onehot(...)`` — LHS is the
      weighted slot one-hot [T*m, n], RHS carries the gradients.
    - PER-TREE gradients (boosting: each batch element has its own margins
      F): ``gh_t`` f32[T, n, c1] + ``Obin = bin_onehot(...)`` — gradients
      ride the LHS ([T*m*c1, n]), the RHS is the gradient-free bin one-hot
      built once per LAUNCH instead of once per round.

    The segment-sum fallback stays on the vmapped ``grow_tree``.
    """
    B = n_bins
    n, d = Xb.shape
    c = (gh.shape[1] if gh_t is None else gh_t.shape[2]) - 1
    T = w_t.shape[0]
    iota_m = jnp.arange(m)
    in_use = iota_m[None, :] < n_active[:, None]                    # [T, m]
    # slot one-hot with slot axis BEFORE rows: flattening needs no transpose
    S = (row_slot[:, None, :] == iota_m[None, :, None]).astype(jnp.float32)
    subtract = pair_hist is not None
    pairs = m // 2
    if subtract:
        # histogram subtraction: the level GEMM's LHS covers only the LIGHT
        # child of each sibling pair (half the slot rows); the heavy sibling
        # is parent - light after the data-axis psum (see _grow_level)
        light_sel = jnp.stack([pair_light, 1.0 - pair_light], axis=-1)
        S_hist = (S.reshape(T, pairs, 2, n) * light_sel[..., None]).sum(2)
        mh = pairs
        record_trace_event("hist_subtracted", "mm_batch",
                           2 * T * pairs * n * (c + 1) * d * B)
    else:
        S_hist = S
        mh = m
    Sw = S_hist * w_t[:, None, :]                                   # [T, mh, n]
    acc_dt = jnp.bfloat16 if _bf16_hist_acc() else jnp.float32
    if acc_dt == jnp.bfloat16:
        record_trace_event("bf16_hist", "mm_batch",
                           2 * T * mh * (c + 1) * d * B)
    if gh_t is None:
        GH = lax.dot_general(Sw.reshape(T * mh, n).astype(Og.dtype), Og,
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=acc_dt)
    else:
        # [T, mh, c1, n]: slot one-hot x per-tree weighted gradients
        L = Sw[:, :, None, :] * gh_t.transpose(0, 2, 1)[:, None, :, :]
        GH = lax.dot_general(L.reshape(T * mh * (c + 1), n).astype(Obin.dtype),
                             Obin, (((1,), (0,)), ((), ())),
                             preferred_element_type=acc_dt)
    # bf16 accumulation ends HERE: psum and split gains stay f32
    GH = GH.astype(jnp.float32).reshape(T, mh, c + 1, d, B)
    # global per-bin stats under a row-sharded launch (see _grow_level);
    # subtracted levels psum only the light half of the payload
    GH = mesh_psum(GH, axis_name)
    if subtract:
        GH_h = pair_hist - GH
        lp = (pair_light > 0.5)[:, :, None, None, None]
        GH = jnp.stack([jnp.where(lp, GH, GH_h),
                        jnp.where(lp, GH_h, GH)],
                       axis=2).reshape(T, m, c + 1, d, B)
    G, H = GH[:, :, :c], GH[:, :, c]                # [T,m,c,d,B], [T,m,d,B]
    GT = G[:, :, :, 0, :].sum(axis=-1)              # [T, m, c]
    HT = H[:, :, 0, :].sum(axis=-1)                 # [T, m]

    GL = jnp.cumsum(G, axis=-1)
    HL = jnp.cumsum(H, axis=-1)
    GR = GT[:, :, :, None, None] - GL
    HR = HT[:, :, None, None] - HL

    lam = reg_lambda_t[:, None, None, None]

    def score(Gp, Hp):
        return (Gp * Gp).sum(axis=2) / (Hp + lam)

    gain = score(GL, HL) + score(GR, HR) \
        - ((GT * GT).sum(axis=2) / (HT + reg_lambda_t[:, None]))[:, :, None, None]
    valid = (HL >= mcw_t[:, None, None, None]) & (HR >= mcw_t[:, None, None, None])
    valid &= feat_mask_t[:, None, :, None] > 0.0
    valid &= jnp.arange(B)[None, None, None, :] < B - 1
    gain = jnp.where(valid, gain, -jnp.inf)
    flat = gain.reshape(T, m, d * B)
    best = jnp.argmax(flat, axis=-1)                                # [T, m]
    best_gain = jnp.max(flat, axis=-1)
    bf = (best // B).astype(jnp.int32)
    bb = (best % B).astype(jnp.int32)
    do_split = (best_gain > gamma_t[:, None]) \
        & (best_gain >= mig_t[:, None] * HT) & in_use
    half = next_cap // 2
    if next_cap < 2 * m and not exact_cap:
        key = jnp.where(do_split, -best_gain, jnp.inf)
        rank = jnp.argsort(jnp.argsort(key, axis=1), axis=1)
        do_split &= rank < half
        k = jnp.cumsum(do_split.astype(jnp.int32), axis=1)
    else:
        k = jnp.cumsum(do_split.astype(jnp.int32), axis=1)
        if next_cap < 2 * m:
            do_split &= k <= half
            k = jnp.minimum(k, half)
    n_split = k[:, -1]
    child_idx = (k - 1) * 2
    left_pool = next_free + child_idx
    right_pool = left_pool + 1
    rec = jnp.stack([jnp.where(do_split, bf, -1),
                     jnp.where(do_split, bb, 0),
                     jnp.where(do_split, left_pool, 0),
                     jnp.where(do_split, right_pool, 0)], axis=-1)  # [T, m, 4]
    nodes = lax.dynamic_update_slice(nodes, rec, (0, slot_base, 0))
    onehot_best = jax.nn.one_hot(best, d * B, dtype=GL.dtype)       # [T, m, dB]
    GL_best = jnp.einsum("tmcx,tmx->tmc", GL.reshape(T, m, c, d * B),
                         onehot_best)
    HL_best = jnp.einsum("tmx,tmx->tm", HL.reshape(T, m, d * B), onehot_best)
    GR_best = GT - GL_best
    HR_best = HT - HL_best
    lval = jnp.where(do_split[:, :, None],
                     -GL_best / (HL_best + reg_lambda_t[:, None])[:, :, None], 0.0)
    rval = jnp.where(do_split[:, :, None],
                     -GR_best / (HR_best + reg_lambda_t[:, None])[:, :, None], 0.0)
    iota_cap = jnp.arange(next_cap)
    pos_l = jnp.where(do_split, child_idx, -1)
    pos_r = jnp.where(do_split, child_idx + 1, -1)
    L_eq = (iota_cap[None, :, None] == pos_l[:, None, :]).astype(leaf_val.dtype)
    R_eq = (iota_cap[None, :, None] == pos_r[:, None, :]).astype(leaf_val.dtype)
    child_vals = jnp.einsum("tpm,tmc->tpc", L_eq, lval) \
        + jnp.einsum("tpm,tmc->tpc", R_eq, rval)          # [T, next_cap, c]
    leaf_val = lax.dynamic_update_slice(leaf_val, child_vals, (0, next_free, 0))
    # route rows: per-row slot data via the S matmul (gathers serialize)
    pack = jnp.concatenate(
        [do_split.astype(jnp.float32)[:, :, None],
         bb.astype(jnp.float32)[:, :, None],
         child_idx.astype(jnp.float32)[:, :, None],
         jax.nn.one_hot(bf, d, dtype=jnp.float32)], axis=-1)        # [T, m, 3+d]
    routed = jnp.einsum("tmn,tmp->tnp", S, pack)                    # [T, n, 3+d]
    splits_here = routed[:, :, 0] > 0.5
    child_r = routed[:, :, 2].astype(jnp.int32)
    row_bin = (routed[:, :, 3:] * Xb[None, :, :]).sum(axis=-1)
    go_right = (row_bin > routed[:, :, 1]).astype(jnp.int32)
    new_row_slot = jnp.where(splits_here, child_r + go_right, -1)
    row_node = jnp.where(splits_here, next_free + child_r + go_right, row_node)
    if want_pairs:
        # parent histograms packed at next-level pair positions (see
        # _grow_level): every other row of the child-packing selector L_eq
        GH_all = jnp.concatenate([G, H[:, :, None]], axis=2).reshape(T, m, -1)
        P_pair = L_eq[:, 0::2, :]                    # [T, next_cap // 2, m]
        new_pair_hist = jnp.einsum("tpm,tmx->tpx", P_pair, GH_all).reshape(
            T, next_cap // 2, c + 1, d, B)
        new_pair_light = jnp.einsum(
            "tpm,tm->tp", P_pair, (HL_best <= HR_best).astype(jnp.float32))
        return (nodes, leaf_val, 2 * n_split, new_row_slot, row_node,
                new_pair_light, new_pair_hist)
    return nodes, leaf_val, 2 * n_split, new_row_slot, row_node


def grow_forest(Xb, g, h, w_t, feat_mask_t, max_depth: int, n_bins: int,
                frontier: int, reg_lambda_t, gamma_t, mcw_t, mig_t,
                exact_cap: bool = False, return_row_node: bool = False,
                gh_t=None, Obin=None, axis_name: Optional[str] = None):
    """Grow T trees together; ONE flat GEMM per level (see header note).

    Shared: Xb int[n, d].  Gradients either SHARED (g f32[n, c], h f32[n] —
    forests) or PER TREE (``gh_t`` f32[T, n, c1] with ``Obin =
    bin_onehot(Xb, n_bins)``; pass g/h as None — boosting).  Per tree:
    w_t f32[T, n], feat_mask_t f32[T, d], reg_lambda/gamma/mcw/mig f32[T].
    Falls back to ``vmap(grow_tree)`` when the matmul histogram path is off
    (CPU).  Returns Tree with leading [T] axis (+ row_node on request).
    """
    Xb = Xb.astype(jnp.int32)
    n, d = Xb.shape
    c = (g.shape[1] if gh_t is None else gh_t.shape[2] - 1)
    c1 = c + 1
    T = w_t.shape[0]
    if not _hist_via_matmul(n, d, n_bins, c1):
        if gh_t is None:
            def one(wt, fm, lam, gam, mcw, mig):
                return grow_tree(Xb, g, h, wt, fm, max_depth, n_bins,
                                 frontier, reg_lambda=lam, gamma=gam,
                                 min_child_weight=mcw, min_info_gain=mig,
                                 Og=None, return_row_node=return_row_node,
                                 exact_cap=exact_cap, axis_name=axis_name)

            return jax.vmap(one)(w_t, feat_mask_t, reg_lambda_t, gamma_t,
                                 mcw_t, mig_t)

        def one(ght, wt, fm, lam, gam, mcw, mig):
            return grow_tree(Xb, ght[:, :c], ght[:, c], wt, fm, max_depth,
                             n_bins, frontier, reg_lambda=lam, gamma=gam,
                             min_child_weight=mcw, min_info_gain=mig,
                             Og=None, return_row_node=return_row_node,
                             exact_cap=exact_cap, axis_name=axis_name)

        return jax.vmap(one)(gh_t, w_t, feat_mask_t, reg_lambda_t, gamma_t,
                             mcw_t, mig_t)
    if gh_t is None:
        gh = jnp.concatenate([g, h[:, None]], axis=1)
        Og = grad_onehot(Xb, gh, n_bins)
        Obin = None
        gw_sum = (g[None, :, :] * w_t[:, :, None]).sum(axis=1)      # [T, c]
        hw_sum = (h[None, :] * w_t).sum(axis=1)                     # [T]
    else:
        gh = None
        Og = None
        if Obin is None:
            Obin = bin_onehot(Xb, n_bins)
        gw_sum = (gh_t[:, :, :c] * w_t[:, :, None]).sum(axis=1)
        hw_sum = (gh_t[:, :, c] * w_t).sum(axis=1)
    gw_sum = mesh_psum(gw_sum, axis_name)
    hw_sum = mesh_psum(hw_sum, axis_name)
    P = _pool_size(max_depth, frontier)
    root_val = -gw_sum / (hw_sum + reg_lambda_t)[:, None]
    nodes = jnp.tile(jnp.asarray([-1, 0, 0, 0], jnp.int32), (T, P, 1))
    leaf_val = jnp.zeros((T, P, c), jnp.float32).at[:, 0].set(root_val)
    row_node = jnp.zeros((T, n), jnp.int32)

    def as_tree(nodes, leaf_val):
        return Tree(split_feat=nodes[:, :, 0], split_bin=nodes[:, :, 1],
                    left=nodes[:, :, 2], right=nodes[:, :, 3],
                    leaf_val=leaf_val)

    if max_depth <= 0:
        tree = as_tree(nodes, leaf_val)
        return (tree, row_node) if return_row_node else tree

    M = frontier
    L = M.bit_length() - 1
    sub = _hist_subtract() and max_depth > 1
    carry = (nodes, leaf_val, jnp.ones((T,), jnp.int32),
             jnp.zeros((T, n), jnp.int32), row_node)
    pl = ph = None
    u = min(max_depth, L)
    for t in range(u):
        out = _grow_level_batch(
            Xb, gh, w_t, feat_mask_t, carry[0], carry[1], (1 << t) - 1,
            (1 << (t + 1)) - 1, *carry[2:], m=1 << t, next_cap=1 << (t + 1),
            n_bins=n_bins, reg_lambda_t=reg_lambda_t, gamma_t=gamma_t,
            mcw_t=mcw_t, mig_t=mig_t, Og=Og, exact_cap=exact_cap,
            gh_t=gh_t, Obin=Obin, axis_name=axis_name,
            pair_light=pl, pair_hist=ph, want_pairs=sub)
        if sub:
            carry, pl, ph = out[:5], out[5], out[6]
        else:
            carry = out
    if max_depth > L:
        if sub:
            def body(t, state):
                sb = M - 1 + (t - L) * M
                return _grow_level_batch(
                    Xb, gh, w_t, feat_mask_t, state[0], state[1], sb, sb + M,
                    *state[2:5], m=M, next_cap=M, n_bins=n_bins,
                    reg_lambda_t=reg_lambda_t, gamma_t=gamma_t, mcw_t=mcw_t,
                    mig_t=mig_t, Og=Og, exact_cap=exact_cap,
                    gh_t=gh_t, Obin=Obin, axis_name=axis_name,
                    pair_light=state[5], pair_hist=state[6], want_pairs=True)

            carry = lax.fori_loop(L, max_depth, body,
                                  tuple(carry) + (pl, ph))[:5]
        else:
            def body(t, carry):
                sb = M - 1 + (t - L) * M
                return _grow_level_batch(
                    Xb, gh, w_t, feat_mask_t, carry[0], carry[1], sb, sb + M,
                    *carry[2:], m=M, next_cap=M, n_bins=n_bins,
                    reg_lambda_t=reg_lambda_t, gamma_t=gamma_t, mcw_t=mcw_t,
                    mig_t=mig_t, Og=Og, exact_cap=exact_cap,
                    gh_t=gh_t, Obin=Obin, axis_name=axis_name)

            carry = lax.fori_loop(L, max_depth, body, carry)
    nodes, leaf_val, row_node = carry[0], carry[1], carry[4]
    tree = as_tree(nodes, leaf_val)
    return (tree, row_node) if return_row_node else tree


# ---------------------------------------------------------------------------
# Random forest — vmap over trees
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("max_depth", "n_bins", "frontier",
                                             "exact_cap"))
def fit_forest(Xb, g, h, w_trees, feat_masks, max_depth: int, n_bins: int,
               frontier: int, reg_lambda: float = 1e-6,
               min_child_weight: float = 1.0, min_info_gain: float = 0.0,
               exact_cap: bool = False) -> Tree:
    """Train all trees of a forest in one launch.

    w_trees: f32[T, n] bootstrap weights; feat_masks: f32[T, d].
    Returns Tree with leading tree axis.
    """

    T = w_trees.shape[0]
    return grow_forest(Xb, g, h, w_trees, feat_masks, max_depth, n_bins,
                       frontier,
                       reg_lambda_t=jnp.full(T, reg_lambda, jnp.float32),
                       gamma_t=jnp.zeros(T, jnp.float32),
                       mcw_t=jnp.full(T, min_child_weight, jnp.float32),
                       mig_t=jnp.full(T, min_info_gain, jnp.float32),
                       exact_cap=exact_cap)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_forest(Xb, forest: Tree, max_depth: int) -> jax.Array:
    """Average the trees' leaf vectors: f32[n, c]."""
    preds = jax.vmap(lambda t: predict_tree(Xb, t, max_depth))(forest)  # [T, n, c]
    return preds.mean(axis=0)


def forest_chunk_size(max_depth: int, n_bins: int, d: int, c: int,
                      frontier: int, budget_bytes: float = 3e9,
                      n_rows: int = 0) -> int:
    """Trees per chunk so one chunk's level tensors fit the budget.

    A level materializes G [M, d, B, c] + cumsums per tree (x3 covers the
    cumsum/gain temporaries) plus, on the batch-GEMM path, the slot one-hot
    [M, n] and its weighted flattening (the ``2 * n_rows`` term).  With
    histogram subtraction on, the carried parent pair histograms add about
    half a level's histograms (the 0.5 bump)."""
    hist_factor = 3.5 if _hist_subtract() else 3.0
    per_tree = frontier * (n_bins * d * (c + 1) * hist_factor + 2 * n_rows) * 4
    return max(1, int(budget_bytes / max(per_tree, 1)))


def balanced_chunk(total: int, chunk_max: int) -> int:
    """Even chunk size: ceil-divide ``total`` into the fewest chunks that
    respect ``chunk_max``, then size chunks evenly so zero-weight padding is
    at most ``n_chunks - 1`` trees (a naive min(total, chunk_max) padded a
    900-tree group to 2 x 635 = 41% waste — round-5 profile)."""
    total = max(int(total), 1)
    n_chunks = -(-total // max(int(chunk_max), 1))
    return -(-total // n_chunks)


@functools.partial(jax.jit,
                   static_argnames=("max_depth", "n_bins", "chunk", "frontier",
                                    "exact_cap"))
def fit_forest_chunked(Xb, g, h, w_trees, feat_masks, mcw_trees, max_depth: int,
                       n_bins: int, chunk: int, frontier: int,
                       reg_lambda: float = 1e-6, mig_trees=None,
                       exact_cap: bool = False) -> Tree:
    """Train an arbitrary tree population with bounded memory: ``lax.map``
    over chunks of ``chunk`` vmapped trees — one compile, sequential chunks.

    The tree axis TT (a multiple of ``chunk``; callers pad with zero-weight
    trees) may interleave folds x grid candidates x bootstrap replicas —
    per-tree ``mcw_trees``/``mig_trees`` carry the grid's min-child-weight
    and min-info-gain, so a whole RF fold x grid sweep is a single launch
    (SURVEY §2.7 axis 2).
    """
    n = Xb.shape[0]
    d = Xb.shape[1]
    if mig_trees is None:
        mig_trees = jnp.zeros_like(mcw_trees)

    def one_chunk(args):
        wts, fms, mcws, migs = args
        lam = jnp.full(wts.shape[0], reg_lambda, jnp.float32)
        gam = jnp.zeros(wts.shape[0], jnp.float32)
        return grow_forest(Xb, g, h, wts, fms, max_depth, n_bins, frontier,
                           reg_lambda_t=lam, gamma_t=gam, mcw_t=mcws,
                           mig_t=migs, exact_cap=exact_cap)

    trees = lax.map(one_chunk, (w_trees.reshape(-1, chunk, n),
                                feat_masks.reshape(-1, chunk, d),
                                mcw_trees.reshape(-1, chunk),
                                mig_trees.reshape(-1, chunk)))
    return jax.tree.map(lambda a: a.reshape((-1,) + a.shape[2:]), trees)


def fit_forest_sharded(mesh, axis_name: str, Xb, g, h, w_trees, feat_masks,
                       mcw_trees, max_depth: int, n_bins: int, chunk: int,
                       frontier: int, reg_lambda: float = 1e-6,
                       mig_trees=None, exact_cap: bool = False) -> Tree:
    """Tree-axis-sharded forest training: each mesh shard grows its slice of
    the tree population with the memory-chunked kernel — zero communication
    (SURVEY §2.7 axis 2; the OpValidator thread pool spread over chips).

    TT must be a multiple of shards * chunk (callers pad with zero-weight
    trees).  Returns the full forest with the tree axis sharded over
    ``axis_name``.
    """
    try:
        from jax import shard_map  # jax >= 0.6
        no_check = {"check_vma": False}
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
        no_check = {"check_rep": False}
    from jax.sharding import PartitionSpec as P

    if mig_trees is None:
        mig_trees = jnp.zeros_like(mcw_trees)

    def local(xb, gg, hh, w, fm, mc, mg):
        return fit_forest_chunked(xb, gg, hh, w, fm, mc, max_depth=max_depth,
                                  n_bins=n_bins, chunk=chunk, frontier=frontier,
                                  reg_lambda=reg_lambda, mig_trees=mg,
                                  exact_cap=exact_cap)

    sm = shard_map(local, mesh=mesh,
                   in_specs=(P(), P(), P(), P(axis_name), P(axis_name),
                             P(axis_name), P(axis_name)),
                   out_specs=P(axis_name), **no_check)
    return sm(Xb, g, h, w_trees, feat_masks, mcw_trees, mig_trees)


@functools.partial(jax.jit, static_argnames=("max_depth", "n_groups"))
def predict_forest_groups(Xb, forest: Tree, max_depth: int, n_groups: int) -> jax.Array:
    """Mean leaf vector per group of trees: f32[n_groups, n, c] — the eval
    half of the batched fold x grid RF sweep (forest axis = n_groups * T)."""
    preds = jax.vmap(lambda t: predict_tree(Xb, t, max_depth))(forest)  # [TT, n, c]
    return preds.reshape((n_groups, -1) + preds.shape[1:]).mean(axis=1)


# ---------------------------------------------------------------------------
# Gradient boosting — lax.scan over rounds
# ---------------------------------------------------------------------------
def _grad_hess(loss: str, F, y, Y_onehot):
    if loss == "squared":
        return (F[:, 0] - y)[:, None], jnp.ones_like(y)
    if loss == "logistic":
        p = jax.nn.sigmoid(F[:, 0])
        return (p - y)[:, None], jnp.maximum(p * (1 - p), 1e-6)
    if loss == "softmax":
        p = jax.nn.softmax(F, axis=-1)
        # scalar hessian approximation: mean over classes of p(1-p)
        return p - Y_onehot, jnp.maximum((p * (1 - p)).mean(axis=-1), 1e-6)
    raise ValueError(f"unknown loss {loss!r}")


def _gbt_impl(Xb, y, w, row_w_rounds, feat_mask_rounds, loss: str, n_rounds: int,
              max_depth: int, n_bins: int, frontier: int, eta, reg_lambda,
              gamma, min_child_weight, base_score: float, n_classes: int,
              min_info_gain=0.0, exact_cap: bool = False,
              axis_name: Optional[str] = None,
              trees_per_round: int = 1,
              init_margins=None) -> Tuple[Tree, jax.Array]:
    """Traceable boosting body shared by fit_gbt and fit_gbt_batch.

    ``trees_per_round`` = K > 1 collapses the boosting chain: the scan takes
    ``n_rounds / K`` steps, each growing K trees against the SAME gradients
    (their round-specific subsample/colsample draws kept) at learning rate
    ``eta / K`` — the boosted-forest round-collapse.  K must divide
    ``n_rounds``.  The stacked tree axis stays [n_rounds, ...] and
    ``predict_gbt`` with ``eta / K`` scores it unchanged.

    ``init_margins`` (f32[n, c], default None) seeds the boosting carry F
    instead of ``base_score`` — a later segment of a checkpointed fit
    resumes from the previous segment's final margins and grows the exact
    trees the unsegmented scan would have (boosting is sequential over F,
    so carrying F is the whole fit state besides the up-front rw/fm draws).
    """
    n = Xb.shape[0]
    c = n_classes if loss == "softmax" else 1
    Y = jax.nn.one_hot(y.astype(jnp.int32), max(c, 2), dtype=jnp.float32) \
        if loss == "softmax" else jnp.zeros((n, 2), jnp.float32)
    F0 = (jnp.asarray(init_margins, jnp.float32) if init_margins is not None
          else jnp.full((n, c), base_score, jnp.float32))
    use_mm = _hist_via_matmul(n, Xb.shape[1], n_bins, c + 1)
    K = int(trees_per_round)

    record_trace_event("gbt_chain", loss, n_rounds // max(K, 1))
    if K > 1:
        if n_rounds % K:
            raise ValueError(
                f"trees_per_round={K} must divide n_rounds={n_rounds}")
        steps = n_rounds // K
        rw_s = row_w_rounds.reshape(steps, K, n)
        fm_s = feat_mask_rounds.reshape(steps, K, -1)
        as_k = lambda v: jnp.broadcast_to(
            jnp.asarray(v, jnp.float32), (K,))

        def step_fn(F, xs):
            rwk, fmk = xs                              # [K, n], [K, d]
            g, hh = _grad_hess(loss, F, y, Y)
            trees, row_node = grow_forest(
                Xb, g, hh, w[None, :] * rwk, fmk, max_depth, n_bins,
                frontier, reg_lambda_t=as_k(reg_lambda), gamma_t=as_k(gamma),
                mcw_t=as_k(min_child_weight), mig_t=as_k(min_info_gain),
                exact_cap=exact_cap, return_row_node=True,
                axis_name=axis_name)
            leaves = jnp.take_along_axis(
                trees.leaf_val, row_node[:, :, None].repeat(c, axis=2),
                axis=1)                                # [K, n, c]
            F = F + (eta / K) * leaves.sum(axis=0)
            return F, trees

        F, trees = lax.scan(step_fn, F0, (rw_s, fm_s))
        # restore the flat [n_rounds, ...] tree axis
        trees = jax.tree.map(
            lambda a: a.reshape((n_rounds,) + a.shape[2:]), trees)
        return trees, F

    def round_fn(F, xs):
        rw, fm = xs
        g, hh = _grad_hess(loss, F, y, Y)
        # gradients change per round, so the shared one-hot is per-round too
        Og = (grad_onehot(Xb, jnp.concatenate([g, hh[:, None]], axis=1),
                          n_bins) if use_mm else None)
        tree, row_node = grow_tree(
            Xb, g, hh, w * rw, fm, max_depth, n_bins, frontier,
            reg_lambda=reg_lambda, gamma=gamma,
            min_child_weight=min_child_weight,
            min_info_gain=min_info_gain, Og=Og, return_row_node=True,
            exact_cap=exact_cap, axis_name=axis_name)
        # row_node is each row's resting node — no predict walk needed
        F = F + eta * tree.leaf_val[row_node]
        return F, tree

    F, trees = lax.scan(round_fn, F0, (row_w_rounds, feat_mask_rounds))
    return trees, F


@functools.partial(jax.jit, static_argnames=("loss", "n_rounds", "max_depth",
                                             "n_bins", "n_classes", "frontier",
                                             "exact_cap", "trees_per_round"))
def fit_gbt(Xb, y, w, row_w_rounds, feat_mask_rounds, loss: str, n_rounds: int,
            max_depth: int, n_bins: int, frontier: int, eta: float = 0.3,
            reg_lambda: float = 1.0, gamma: float = 0.0,
            min_child_weight: float = 1.0, base_score: float = 0.0,
            n_classes: int = 1, min_info_gain: float = 0.0,
            exact_cap: bool = False,
            trees_per_round: int = 1,
            init_margins=None) -> Tuple[Tree, jax.Array]:
    """XGBoost-style boosting: scan over rounds, one histogram tree per round.

    row_w_rounds: f32[R, n] subsample weights per round; feat_mask_rounds:
    f32[R, d] colsample masks.  Multiclass uses multi-output trees (leaf
    vector per class) — a TPU-friendly variant of per-class tree sets.
    ``trees_per_round`` = K > 1 grows K trees per boosting step at eta / K
    (round-collapse; callers scoring the stacked trees must scale eta the
    same way).  ``init_margins`` seeds the carry F for segmented
    (checkpoint-resumable) fits.  Returns (stacked Tree [R, ...], final
    margins F [n, c]).
    """
    return _gbt_impl(Xb, y, w, row_w_rounds, feat_mask_rounds, loss, n_rounds,
                     max_depth, n_bins, frontier, eta, reg_lambda, gamma,
                     min_child_weight, base_score, n_classes,
                     min_info_gain=min_info_gain, exact_cap=exact_cap,
                     trees_per_round=trees_per_round,
                     init_margins=init_margins)


def _gbt_batch_impl(Xb, y, w_batch, row_w_rounds, feat_mask_rounds, loss: str,
                    n_rounds: int, max_depth: int, n_bins: int, frontier: int,
                    eta_b, reg_lambda_b, gamma_b, min_child_weight_b,
                    base_score_b=None, n_classes: int = 1,
                    min_info_gain_b=None, exact_cap: bool = False,
                    axis_name: Optional[str] = None,
                    trees_per_round: int = 1) -> jax.Array:
    """Traceable body of :func:`fit_gbt_batch` — also called directly by the
    fused sweep (ops/sweep.py) with ``axis_name`` set on the row-sharded
    path and ``trees_per_round`` > 1 for round-collapsed GBT groups.

    With K = ``trees_per_round``, every scan step grows B * K trees as one
    flat-GEMM forest (K per candidate, against that candidate's step
    gradients, each keeping its own round subsample/colsample draw) and
    applies their mean at learning rate ``eta_b`` (i.e. eta / K each) — the
    boosted-forest round-collapse.  K = 1 reproduces the per-round scan
    bit-for-bit (the K-generalized reshapes are layout no-ops).
    """
    if base_score_b is None:
        base_score_b = jnp.zeros(w_batch.shape[0], jnp.float32)
    if min_info_gain_b is None:
        min_info_gain_b = jnp.zeros(w_batch.shape[0], jnp.float32)

    Xb = Xb.astype(jnp.int32)
    n, d = Xb.shape
    B = w_batch.shape[0]
    c = n_classes if loss == "softmax" else 1
    K = int(trees_per_round)
    if n_rounds % max(K, 1):
        raise ValueError(
            f"trees_per_round={K} must divide n_rounds={n_rounds}")
    if not _hist_via_matmul(n, d, n_bins, c + 1):
        # segment-sum backends keep the per-element vmap formulation
        def one(w, eta, lam, gam, mcw, base, mig):
            _, F = _gbt_impl(Xb, y, w, row_w_rounds, feat_mask_rounds, loss,
                             n_rounds, max_depth, n_bins, frontier, eta, lam,
                             gam, mcw, base, n_classes, min_info_gain=mig,
                             exact_cap=exact_cap, axis_name=axis_name,
                             trees_per_round=K)
            return F

        return jax.vmap(one)(w_batch, eta_b, reg_lambda_b, gamma_b,
                             min_child_weight_b, base_score_b, min_info_gain_b)

    # batch-native boosting: every step grows its B * K trees as ONE
    # flat-GEMM forest (per-tree gradients ride the LHS); the gradient-free
    # bin one-hot RHS is built ONCE for the whole launch instead of per
    # round (see bin_onehot / _grow_level_batch)
    Y = jax.nn.one_hot(y.astype(jnp.int32), max(c, 2), dtype=jnp.float32) \
        if loss == "softmax" else jnp.zeros((n, 2), jnp.float32)
    Obin = bin_onehot(Xb, n_bins)
    F0 = jnp.broadcast_to(base_score_b[:, None, None], (B, n, c)).astype(jnp.float32)
    steps = n_rounds // K
    record_trace_event("gbt_chain", loss, steps)
    rw_s = row_w_rounds.reshape(steps, K, n)
    fm_s = feat_mask_rounds.reshape(steps, K, d)

    def step_fn(F, xs):
        rwk, fmk = xs                                  # [K, n], [K, d] shared
        if loss == "squared":
            gb = F[..., 0] - y[None, :]
            hb = jnp.ones((B, n), jnp.float32)
            g3 = gb[..., None]
        elif loss == "logistic":
            p = jax.nn.sigmoid(F[..., 0])
            g3 = (p - y[None, :])[..., None]
            hb = jnp.maximum(p * (1 - p), 1e-6)
        else:  # softmax
            p = jax.nn.softmax(F, axis=-1)
            g3 = p - Y[None, :, :]
            hb = jnp.maximum((p * (1 - p)).mean(axis=-1), 1e-6)
        gh_t = jnp.concatenate([g3, hb[..., None]], axis=-1)   # [B, n, c1]
        # candidate-major tree axis [B * K]: candidate b's K trees share its
        # gradients but keep their own round draws
        gh_T = jnp.repeat(gh_t, K, axis=0)
        w_T = (w_batch[:, None, :] * rwk[None, :, :]).reshape(B * K, n)
        fm_T = jnp.broadcast_to(fmk[None, :, :], (B, K, d)).reshape(B * K, d)
        tree, row_node = grow_forest(
            Xb, None, None, w_T, fm_T, max_depth, n_bins,
            frontier, reg_lambda_t=jnp.repeat(reg_lambda_b, K),
            gamma_t=jnp.repeat(gamma_b, K),
            mcw_t=jnp.repeat(min_child_weight_b, K),
            mig_t=jnp.repeat(min_info_gain_b, K),
            exact_cap=exact_cap, return_row_node=True,
            gh_t=gh_T, Obin=Obin, axis_name=axis_name)
        # leaf lookup via one gather per step (row_node tracks leaves)
        leaves = jnp.take_along_axis(
            tree.leaf_val, row_node[:, :, None].repeat(c, axis=2), axis=1)
        leaves = leaves.reshape(B, K, n, c).sum(axis=1)
        F = F + (eta_b / K)[:, None, None] * leaves
        return F, None

    F, _ = lax.scan(step_fn, F0, (rw_s, fm_s))
    return F


@functools.partial(jax.jit, static_argnames=("loss", "n_rounds", "max_depth",
                                             "n_bins", "n_classes", "frontier",
                                             "exact_cap", "trees_per_round"))
def fit_gbt_batch(Xb, y, w_batch, row_w_rounds, feat_mask_rounds, loss: str,
                  n_rounds: int, max_depth: int, n_bins: int, frontier: int,
                  eta_b, reg_lambda_b, gamma_b, min_child_weight_b,
                  base_score_b=None, n_classes: int = 1,
                  min_info_gain_b=None, exact_cap: bool = False,
                  trees_per_round: int = 1) -> jax.Array:
    """The fold x grid boosting sweep as ONE launch (the OpValidator
    thread-pool analog for boosted models — SURVEY §2.7 axis 2).

    ``w_batch`` f32[B, n] carries fold-mask x sample weights per batch
    element; ``eta_b``/``reg_lambda_b``/``gamma_b``/``min_child_weight_b``
    f32[B] are the grid's dynamic hyperparameters (static shape params —
    depth, rounds, bins, trees_per_round — must match across the batch; the
    caller groups grids accordingly).  Returns final margins F f32[B, n, c]
    on the FULL dataset, from which fold-validation slices are taken.
    """
    return _gbt_batch_impl(Xb, y, w_batch, row_w_rounds, feat_mask_rounds,
                           loss, n_rounds, max_depth, n_bins, frontier,
                           eta_b, reg_lambda_b, gamma_b, min_child_weight_b,
                           base_score_b=base_score_b, n_classes=n_classes,
                           min_info_gain_b=min_info_gain_b,
                           exact_cap=exact_cap,
                           trees_per_round=trees_per_round)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_gbt(Xb, trees: Tree, max_depth: int, eta: float,
                base_score: float = 0.0) -> jax.Array:
    """Sum of shrunken tree outputs: f32[n, c]."""
    preds = jax.vmap(lambda t: predict_tree(Xb, t, max_depth))(trees)  # [R, n, c]
    return base_score + eta * preds.sum(axis=0)


# ---------------------------------------------------------------------------
# Subsampling masks — DEVICE-side RNG (threefry: identical draws on every
# backend).  These are traceable and run INSIDE the fit kernels, so the
# sweep never uploads [T, n] bootstrap matrices over the wire (measured
# ~70 ms per device_put on a tunneled backend — round-5 latency probe).
# fit_arrays and the fused sweep interpreter share the same (seed -> key ->
# draw) scheme, so the batched fold x grid path trains on EXACTLY the same
# bootstraps as the per-candidate loop path (tests/test_batched_tree_sweep).
# ---------------------------------------------------------------------------
def rng_keys(seed: int):
    """(bootstrap_key, feature_key) — the canonical split both paths use."""
    kb, kf = jax.random.split(jax.random.PRNGKey(jnp.uint32(seed)))
    return kb, kf


def bootstrap_weights(key, n: int, n_trees: int, bootstrap: bool = True,
                      rate: float = 1.0) -> jax.Array:
    """Poisson(rate) bootstrap weights — the with-replacement limit Spark's
    BaggedPoint uses, with ``rate`` = RF subsamplingRate (each tree sees a
    bootstrap of expected size ``n * rate``).  Traceable."""
    if not bootstrap:
        return jnp.ones((n_trees, n), jnp.float32)
    return jax.random.poisson(key, rate, (n_trees, n)).astype(jnp.float32)


def feature_masks(key, d: int, n_trees: int, frac: float) -> jax.Array:
    """Per-tree feature-subset masks (featureSubsetStrategy / colsample):
    exactly k features per tree via a random-key threshold.  Traceable."""
    if frac >= 1.0:
        return jnp.ones((n_trees, d), jnp.float32)
    k = max(1, int(round(frac * d)))
    r = jax.random.uniform(key, (n_trees, d))
    thresh = jnp.sort(r, axis=1)[:, k - 1: k]
    return (r <= thresh).astype(jnp.float32)


def subsample_weights(key, n: int, n_rounds: int, frac: float) -> jax.Array:
    """Per-round row-subsample masks (GBT subsamplingRate / XGB subsample).
    Traceable."""
    if frac >= 1.0:
        return jnp.ones((n_rounds, n), jnp.float32)
    return (jax.random.uniform(key, (n_rounds, n)) < frac).astype(jnp.float32)


# ---------------------------------------------------------------------------
# FLOPs accounting (bench MFU): wrap the tree kernels so every call records
# its XLA cost_analysis when utils.flops is enabled.  NOTE: tree-histogram
# work is scatter/cumsum-heavy (VPU, not MXU); the recorded flops are XLA's
# arithmetic count for the optimized HLO, the honest numerator for an
# arithmetic-utilization figure rather than an MXU duty cycle.
# ---------------------------------------------------------------------------
from ..utils import flops as _flops  # noqa: E402

for _n in ("fit_forest", "fit_forest_chunked", "fit_gbt", "fit_gbt_batch",
           "predict_forest", "predict_forest_groups", "predict_gbt"):
    globals()[_n] = _flops.wrap(f"trees.{_n}", globals()[_n])
del _n
