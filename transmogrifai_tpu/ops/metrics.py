"""Device-side batched validation metrics for the fold x grid sweep.

Reference parity: the metric MATH mirrors
evaluators/OpBinaryClassificationEvaluator.scala:56 (AuROC/AuPR via Spark
BinaryClassificationMetrics' rank/threshold curves) and
OpRegressionEvaluator.scala:55 — but where the reference evaluates each
trained model on a separate Spark job (OpValidator.scala:299-357), here ALL
fold x candidate validation scores are evaluated in ONE jitted program and
the sweep pulls a single [F, C] metrics block to the host.

This removes the per-candidate device->host round trips that dominated the
sweep's wall-clock (round-4 VERDICT weak #2: ~84 transfers + host sorts per
Titanic rep): metric evaluation is a [F, C, n] sort + cumsum pipeline, tiny
next to training, and lets XLA dispatch the training launches of successive
model families back-to-back with no host sync between them.

Semantics notes (validated against the host evaluators in
tests/test_device_metrics.py):

- Excluded rows (train rows of the fold, splitter-dropped rows) get score
  ``-inf`` and weight 0.  They sort below every real score, so validation
  ranks are the full-array ranks minus the excluded count; AuROC's midrank
  tie correction and AuPR's distinct-threshold steps are unaffected.
- AuROC uses the rank statistic with midrank tie correction — identical to
  ``evaluators.classification.roc_auc``.
- AuPR is the step-wise area with one point per DISTINCT threshold (Spark
  BinaryClassificationMetrics style) — identical to
  ``evaluators.classification.pr_auc``.
- ``strict`` per-candidate flags choose ``score > 0.5`` vs ``score >= 0.5``
  for the Error/Precision/Recall/F1 class decision, matching each family's
  host ``predict_arrays`` convention (forests argmax -> strict; logistic
  ``p >= 0.5`` -> non-strict).
"""
from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from ..utils import flops

__all__ = ["binary_grid_metrics", "regression_grid_metrics",
           "multiclass_grid_metrics", "BINARY_METRICS", "REGRESSION_METRICS",
           "MULTICLASS_METRICS"]

#: metric order of the stacked output row (binary_grid_metrics)
BINARY_METRICS = ("AuROC", "AuPR", "Error", "Precision", "Recall", "F1")
#: metric order for regression_grid_metrics
REGRESSION_METRICS = ("RootMeanSquaredError", "MeanSquaredError", "R2",
                      "MeanAbsoluteError")
#: metric order for multiclass_grid_metrics
MULTICLASS_METRICS = ("F1", "Precision", "Recall", "Error")


def _binary_one(y, s, vm, strict):
    """Metrics for ONE (fold, candidate): y f32[n] in {0,1}, s f32[n] class-1
    score, vm f32[n] validation weights, strict f32 scalar."""
    n = y.shape[0]
    neg_inf = jnp.float32(-jnp.inf)
    sv = jnp.where(vm > 0, s, neg_inf)
    wpos = vm * y
    wneg = vm * (1.0 - y)
    npos = wpos.sum()
    nneg = wneg.sum()
    n_exc = (1.0 - vm).sum()

    order = jnp.argsort(sv)  # ascending; excluded (-inf) first
    ss = sv[order]
    ys = y[order]
    vs = vm[order]

    # ---- AuROC: rank statistic with midrank ties --------------------------
    lo = jnp.searchsorted(ss, ss, side="left").astype(jnp.float32)
    hi = jnp.searchsorted(ss, ss, side="right").astype(jnp.float32)
    midrank = (lo + hi + 1.0) * 0.5          # 1-based rank in the full array
    rank_val = midrank - n_exc               # rank among validation rows
    r_pos = (vs * ys * rank_val).sum()
    auroc = jnp.where(
        (npos > 0) & (nneg > 0),
        (r_pos - npos * (npos + 1.0) * 0.5) / jnp.maximum(npos * nneg, 1.0),
        0.0)

    # ---- AuPR: step-wise over distinct thresholds, descending -------------
    sd = ss[::-1]
    yd = ys[::-1]
    vd = vs[::-1]
    tp = jnp.cumsum(yd * vd)
    fp = jnp.cumsum((1.0 - yd) * vd)
    finite = sd > neg_inf
    nxt = jnp.concatenate([sd[1:], jnp.full((1,), neg_inf, sd.dtype)])
    distinct = (sd != nxt) & finite          # last index of each tie group
    prec_c = tp / jnp.maximum(tp + fp, 1.0)
    rec_c = tp / jnp.maximum(npos, 1.0)
    idx = jnp.arange(n)
    dmark = jnp.where(distinct, idx, -1)
    run = jax.lax.cummax(dmark)              # inclusive last-distinct index
    prev = jnp.concatenate([jnp.full((1,), -1), run[:-1]])
    r_prev = jnp.where(prev >= 0, rec_c[jnp.maximum(prev, 0)], 0.0)
    aupr = jnp.where(
        npos > 0,
        jnp.where(distinct, prec_c * (rec_c - r_prev), 0.0).sum(), 0.0)

    # ---- thresholded class decision ---------------------------------------
    pred1 = jnp.where(strict > 0, (s > 0.5), (s >= 0.5)).astype(jnp.float32)
    tp_c = (vm * y * pred1).sum()
    fp_c = (vm * (1.0 - y) * pred1).sum()
    fn_c = (vm * y * (1.0 - pred1)).sum()
    nv = jnp.maximum(npos + nneg, 1.0)
    err = (fp_c + fn_c) / nv
    precision = jnp.where(tp_c + fp_c > 0, tp_c / jnp.maximum(tp_c + fp_c, 1.0), 0.0)
    recall = jnp.where(tp_c + fn_c > 0, tp_c / jnp.maximum(tp_c + fn_c, 1.0), 0.0)
    f1 = jnp.where(precision + recall > 0,
                   2.0 * precision * recall / jnp.maximum(precision + recall, 1e-30),
                   0.0)
    return jnp.stack([auroc, aupr, err, precision, recall, f1])


@jax.jit
def _binary_grid_metrics(y, scores, val_w, strict_c):
    """y f32[n]; scores f32[F, C, n]; val_w f32[F, n]; strict_c f32[C].
    Returns f32[F, C, 6] in BINARY_METRICS order."""
    per_c = jax.vmap(_binary_one, in_axes=(None, 0, None, 0))
    per_f = jax.vmap(per_c, in_axes=(None, 0, 0, None))
    return per_f(y, scores, val_w, strict_c)


def binary_grid_metrics(y, scores, val_w, strict_c) -> Dict[str, jax.Array]:
    out = _binary_grid_metrics(y, scores, val_w, strict_c)
    flops.record("metrics.binary_grid_metrics", _binary_grid_metrics,
                 y, scores, val_w, strict_c)
    return {m: out[..., i] for i, m in enumerate(BINARY_METRICS)}


def _regression_one(y, p, vm):
    nv = jnp.maximum(vm.sum(), 1.0)
    err = (p - y) * vm
    mse = (err ** 2).sum() / nv
    mae = jnp.abs(err).sum() / nv
    ybar = (y * vm).sum() / nv
    ss_tot = ((y - ybar) ** 2 * vm).sum()
    r2 = jnp.where(ss_tot > 0, 1.0 - (err ** 2).sum() / jnp.maximum(ss_tot, 1e-30), 0.0)
    return jnp.stack([jnp.sqrt(mse), mse, r2, mae])


@jax.jit
def _regression_grid_metrics(y, preds, val_w):
    per_c = jax.vmap(_regression_one, in_axes=(None, 0, None))
    per_f = jax.vmap(per_c, in_axes=(None, 0, 0))
    return per_f(y, preds, val_w)


def regression_grid_metrics(y, preds, val_w) -> Dict[str, jax.Array]:
    """y f32[n]; preds f32[F, C, n]; val_w f32[F, n] -> {metric: f32[F, C]}."""
    out = _regression_grid_metrics(y, preds, val_w)
    flops.record("metrics.regression_grid_metrics", _regression_grid_metrics,
                 y, preds, val_w)
    return {m: out[..., i] for i, m in enumerate(REGRESSION_METRICS)}


def _multiclass_one(y_onehot, prob, vm):
    """Weighted-average P/R/F1 + Error for ONE (fold, candidate).

    y_onehot f32[n, k]; prob f32[n, k] (argmax decides); vm f32[n].
    Spark MulticlassMetrics semantics: per-class P/R/F1 weighted by class
    frequency in the validation rows.
    """
    k = y_onehot.shape[1]
    pred = jnp.argmax(prob, axis=-1)
    pred_onehot = jax.nn.one_hot(pred, k, dtype=jnp.float32)
    w = vm[:, None]
    tp = (y_onehot * pred_onehot * w).sum(axis=0)          # [k]
    fp = ((1.0 - y_onehot) * pred_onehot * w).sum(axis=0)
    fn = (y_onehot * (1.0 - pred_onehot) * w).sum(axis=0)
    cls_n = (y_onehot * w).sum(axis=0)
    nv = jnp.maximum(vm.sum(), 1.0)
    wgt = cls_n / nv
    p = jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1.0), 0.0)
    r = jnp.where(tp + fn > 0, tp / jnp.maximum(tp + fn, 1.0), 0.0)
    f = jnp.where(p + r > 0, 2.0 * p * r / jnp.maximum(p + r, 1e-30), 0.0)
    err = 1.0 - (y_onehot * pred_onehot * w).sum() / nv
    return jnp.stack([(f * wgt).sum(), (p * wgt).sum(), (r * wgt).sum(), err])


@jax.jit
def _multiclass_grid_metrics(y_onehot, probs, val_w):
    per_c = jax.vmap(_multiclass_one, in_axes=(None, 0, None))
    per_f = jax.vmap(per_c, in_axes=(None, 0, 0))
    return per_f(y_onehot, probs, val_w)


def multiclass_grid_metrics(y_onehot, probs, val_w) -> Dict[str, jax.Array]:
    """y_onehot f32[n, k]; probs f32[F, C, n, k]; val_w f32[F, n]
    -> {metric: f32[F, C]} in MULTICLASS_METRICS order."""
    out = _multiclass_grid_metrics(y_onehot, probs, val_w)
    flops.record("metrics.multiclass_grid_metrics", _multiclass_grid_metrics,
                 y_onehot, probs, val_w)
    return {m: out[..., i] for i, m in enumerate(MULTICLASS_METRICS)}
