"""Package."""
