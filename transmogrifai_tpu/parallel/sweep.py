"""Sharded model-grid sweep — the north-star hot path on TPU.

The reference trains its ModelSelector grid as JVM-thread Futures: numFolds x
models x param-grids fits throttled by an 8-thread pool
(OpValidator.scala:299-357, ValidatorParamDefaults.Parallelism:378).  Here the
same sweep is ONE compiled XLA program:

- `vmap` over the hyperparameter grid (every candidate trains simultaneously
  on the MXU — the fits are identical static-shape programs),
- `vmap` over CV folds (fold membership is a weight mask, so all folds train
  on the same resident data; no data movement between folds),
- sharding over the mesh ``model`` axis spreads candidates across chips with
  zero communication; data replicated (tabular X fits in HBM easily).

Fold masking trick: fold k's training set is encoded as sample_weight zeroing
held-out rows — k-fold CV needs no gather/scatter, just n_folds weight
vectors.  Evaluation likewise masks the complement.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import linear as L
from .mesh import MODEL_AXIS, make_mesh, pad_to_multiple


class GridFit(NamedTuple):
    """Stacked fitted parameters for a grid of candidates: coef [g, d],
    intercept [g, 1] (binary) — leading axis is the grid."""

    coef: jax.Array
    intercept: jax.Array


def make_fold_weights(n: int, n_folds: int, seed: int = 42,
                      stratify_labels: Optional[np.ndarray] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """(train_w [n_folds, n], val_w [n_folds, n]) 0/1 mask pairs.

    Stratified assignment matches the reference's label-stratified kFold
    option (OpValidator stratify, OpCrossValidation.scala:200-236): rows of
    each class are dealt round-robin across folds.
    """
    rng = np.random.default_rng(seed)
    assign = np.empty(n, dtype=np.int64)
    if stratify_labels is not None:
        labels = np.asarray(stratify_labels)
        for cls in np.unique(labels):
            idx = np.where(labels == cls)[0]
            rng.shuffle(idx)
            assign[idx] = np.arange(idx.size) % n_folds
    else:
        assign = rng.permutation(n) % n_folds
    val = np.stack([(assign == k).astype(np.float32) for k in range(n_folds)])
    train = 1.0 - val
    return train, val


def fit_logistic_grid_folds(X, y, train_w, l2_grid, max_iter: int = 30):
    """Train every (fold, l2) logistic candidate in one XLA program.

    X: f32[n, d]; y: f32[n]; train_w: f32[n_folds, n]; l2_grid: f32[g].
    Returns coef [n_folds, g, d], intercept [n_folds, g, 1].  Thin wrapper
    over the shared fold×grid kernel in ops/linear.py.
    """
    res = L.fit_logistic_grid_folds_newton(X, y, train_w, l2_grid, max_iter=max_iter)
    return res.coef, res.intercept


@functools.partial(jax.jit, static_argnames=())
def eval_logistic_grid_folds(X, y, val_w, coef, intercept):
    """Masked validation error for every (fold, candidate): f32[n_folds, g]."""

    def eval_one(w, c, b):
        z = X @ c + b[0]
        pred = (z >= 0.0).astype(jnp.float32)
        wrong = (pred != y).astype(jnp.float32)
        return jnp.sum(wrong * w) / jnp.maximum(jnp.sum(w), 1.0)

    ev_grid = jax.vmap(eval_one, in_axes=(None, 0, 0))
    ev_all = jax.vmap(ev_grid, in_axes=(0, 0, 0))
    return ev_all(val_w, coef, intercept)


def sharded_logistic_sweep(X: np.ndarray, y: np.ndarray, l2_grid: np.ndarray,
                           n_folds: int = 3, mesh=None, max_iter: int = 30,
                           seed: int = 42):
    """Full CV sweep with the grid axis sharded over the mesh ``model`` axis.

    Returns (mean_val_error [g], coef [folds, g, d], intercept [folds, g, 1]).
    On one device this is a plain vmap; on a pod slice each chip trains
    |grid| / n_model candidates (SURVEY §2.7 axis 2).
    """
    mesh = mesh or make_mesh(n_data=1, n_model=1)
    n_model = mesh.shape[MODEL_AXIS]
    l2_pad, g = pad_to_multiple(np.asarray(l2_grid, np.float32), n_model)
    train_w, val_w = make_fold_weights(len(y), n_folds, seed=seed, stratify_labels=y)

    Xd = jnp.asarray(X, jnp.float32)
    yd = jnp.asarray(y, jnp.float32)
    grid_sh = NamedSharding(mesh, P(MODEL_AXIS))
    repl = NamedSharding(mesh, P())
    l2_dev = jax.device_put(jnp.asarray(l2_pad), grid_sh)
    Xd = jax.device_put(Xd, repl)
    yd = jax.device_put(yd, repl)
    tw = jax.device_put(jnp.asarray(train_w), repl)
    vw = jax.device_put(jnp.asarray(val_w), repl)

    coef, intercept = fit_logistic_grid_folds(Xd, yd, tw, l2_dev, max_iter=max_iter)
    err = eval_logistic_grid_folds(Xd, yd, vw, coef, intercept)
    mean_err = np.asarray(err).mean(axis=0)[:g]
    return mean_err, np.asarray(coef)[:, :g], np.asarray(intercept)[:, :g]
