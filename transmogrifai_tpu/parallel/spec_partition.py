"""Cost-balanced partitioning of a fused sweep spec across mesh shards.

The fused one-launch sweep (ops/sweep.py) collapses the whole fold x grid
ModelSelector sweep into one XLA program — but one program runs on ONE chip.
This module is the multi-chip step: split the static ``spec`` into one
sub-spec per mesh ``model`` shard so every chip compiles and runs its own
(smaller) fused program, with the candidate axis divided by PREDICTED cost
rather than by count.

Why a cost model and not round-robin: the default reference grid is wildly
heterogeneous — a depth-12/50-tree forest candidate costs ~6000x a FISTA
candidate (XLA ``cost_analysis``, see impl/sweep_fragments constants), so
count-balanced shards would leave most chips idle behind the one holding the
deep forests.  TpuGraphs (arXiv:2308.13490) and the learned-TPU-cost-model
line (arXiv:2008.01040) show static cost models predict relative XLA program
cost well; the fragment grammar gives us the exact static shape of every
candidate for free, so a calibrated analytic model is enough.

Algorithm: LPT (longest-processing-time) greedy at CANDIDATE granularity —
units (``impl/sweep_fragments.spec_units``) expand to per-candidate atoms,
sorted by descending predicted cost, each assigned to the least-loaded
shard.  Fragments and tree groups are split via ``build_subspec`` (per-shard
re-packed blobs), so ANY candidate subset is expressible.  On the default
LR+RF+XGB grid this lands within a few percent of the mean at 2/4/8 shards
(unit-tested bound: max <= 1.3x mean).

The XGBoost sequential-rounds chain (previously a known non-goal here) is
now attacked at the kernel level: a boosting group's data-dependent chain is
rounds / trees_per_round x depth levels — round-collapse (gbt group field
``trees_per_round``, env ``TMOG_GBT_ROUND_COLLAPSE``) shortens it and
histogram subtraction halves each level's histogram build (ops/trees).
``impl.sweep_fragments._gbt_group_cost`` folds both into the unit costs this
partitioner balances; balance here is still FLOP balance (what
``utils/flops`` reports), and the residual chain overlaps with other
shards' work under async dispatch.
"""
from __future__ import annotations

import heapq
import math
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs import registry as obs_registry
from ..obs import trace


@dataclass
class ShardSpec:
    """One shard's executable slice of a fused sweep."""

    spec: tuple                 #: sub-spec (same grammar as ops/sweep)
    blob: np.ndarray            #: per-shard re-packed f32 hyperparameter blob
    cis: Tuple[int, ...]        #: global candidate index of each local candidate
    cost: float                 #: predicted cost (cost-model units)
    #: device slot this shard was balanced FOR (weighted partitions only);
    #: None = positional (shard i -> devices[i]), the unweighted contract
    slot: Optional[int] = None

    @property
    def n_candidates(self) -> int:
        return len(self.cis)


def predicted_balance(shards: List[ShardSpec]) -> Tuple[float, float]:
    """(max shard cost, mean shard cost) over the partition."""
    costs = [s.cost for s in shards]
    if not costs:
        return 0.0, 0.0
    return max(costs), float(np.mean(costs))


#: explicit per-unit cost override (tests / embedding callers); wins over
#: the env-activated learned model.  ``None`` = analytic ``spec_units``.
_COST_PROVIDER: Optional[Callable] = None


def set_cost_provider(fn: Optional[Callable]) -> Optional[Callable]:
    """Install ``fn(SweepUnit) -> per-candidate cost`` (None restores the
    analytic default); returns the previous provider."""
    global _COST_PROVIDER
    prev, _COST_PROVIDER = _COST_PROVIDER, fn
    return prev


def _resolve_cost_provider() -> Tuple[Optional[Callable], Optional[str]]:
    """(provider, source-label).  (None, None) — the bit-identical analytic
    path — unless a provider was set explicitly or ``TMOG_COSTMODEL=1``
    loads an artifact; model failures record a ``costmodel`` fallback and
    degrade to (None, None)."""
    if _COST_PROVIDER is not None:
        return _COST_PROVIDER, "explicit"
    try:
        from .. import costmodel

        if not costmodel.enabled():
            return None, None
        m = costmodel.active_model()
        if m is None:
            return None, None
        return (lambda u: u.per_cand * m.unit_scale(u.kind)), "learned"
    except Exception as e:  # never let cost lookup break partitioning
        obs_registry.record_fallback("costmodel", "provider_resolve_failed",
                                     error=repr(e))
        return None, None


def _apply_cost_provider(units, provider: Callable, source: str) -> None:
    """Replace every unit's ``per_cand`` with the provider's estimate;
    non-finite/non-positive estimates (or a raising provider) leave ALL
    analytic costs in place and record why."""
    new_costs = []
    for u in units:
        try:
            c = float(provider(u))
        except Exception as e:
            obs_registry.record_fallback("costmodel", "provider_raised",
                                         source=source, error=repr(e))
            return
        if not (math.isfinite(c) and c > 0.0):
            obs_registry.record_fallback("costmodel", "provider_bad_cost",
                                         source=source, cost=repr(c))
            return
        new_costs.append(c)
    for u, c in zip(units, new_costs):
        u.per_cand = c


def partition_spec(spec, blob: np.ndarray, n_shards: int, n_rows: int,
                   n_features: int, n_folds: int,
                   device_weights: Optional[List[float]] = None
                   ) -> List[ShardSpec]:
    """Split ``spec`` into <= ``n_shards`` cost-balanced sub-specs.

    Every global candidate lands in exactly one shard; shard-local candidate
    order is ascending global order (``ShardSpec.cis`` maps back).  Shards
    that would receive no candidates are dropped, so the result may be
    shorter than ``n_shards`` for tiny grids.

    Costs come from the analytic ``spec_units`` constants unless a cost
    provider resolves (``set_cost_provider`` or the ``TMOG_COSTMODEL=1``
    learned model) — with no provider the analytic floats are never
    touched, so the default partition is bit-identical to the pre-costmodel
    behavior.

    ``device_weights`` (one slowdown multiplier per shard slot, from
    ``resilience.health.partition_weights``) makes LPT balance *effective*
    walls: an atom lands on the slot minimizing ``(load + cost) * weight``,
    so a 2x-slow chip gets ~half the work.  ``None`` — or all weights 1.0 —
    runs the exact unweighted heap path, byte-identical to before; weighted
    shards carry their slot in ``ShardSpec.slot`` so the launcher maps each
    shard back to the device it was balanced for even when empty shards
    drop out.
    """
    from ..impl.sweep_fragments import build_subspec, spec_units

    weights: Optional[List[float]] = None
    if device_weights is not None:
        ws = [float(w) for w in device_weights[:n_shards]]
        ws += [1.0] * (n_shards - len(ws))
        if any(w != 1.0 for w in ws):
            weights = [max(w, 1e-6) for w in ws]

    provider, source = _resolve_cost_provider()
    with trace.span("sweep.partition", shards=int(n_shards),
                    rows=int(n_rows), costmodel=source or "",
                    weighted=weights is not None) as sp:
        units = spec_units(spec, n_rows, n_features, n_folds)
        if provider is not None:
            _apply_cost_provider(units, provider, source)
        if n_shards <= 1:
            cis = tuple(sorted(ci for u in units for ci in u.cis))
            return [ShardSpec(spec, np.asarray(blob, np.float32), cis,
                              sum(u.cost for u in units))]

        # LPT greedy over per-candidate atoms: (cost, unit, position-in-unit)
        atoms = [(u.per_cand, u, p) for u in units
                 for p in range(len(u.cis))]
        atoms.sort(key=lambda a: -a[0])
        # picks[shard][unit.key] -> positions
        picks: List[Dict[Tuple[int, Optional[int]], List[int]]] = [
            {} for _ in range(n_shards)]
        loads = [0.0] * n_shards
        if weights is None:
            # heap of (load, shard_index) — the exact historical path
            heap = [(0.0, s) for s in range(n_shards)]
            heapq.heapify(heap)
            for cost, unit, pos in atoms:
                load, s = heapq.heappop(heap)
                picks[s].setdefault(unit.key, []).append(pos)
                loads[s] = load + cost
                heapq.heappush(heap, (loads[s], s))
        else:
            # weighted LPT: argmin effective wall after placement; linear
            # scan (n_shards is the chip count, single digits)
            for cost, unit, pos in atoms:
                s = min(range(n_shards),
                        key=lambda i: ((loads[i] + cost) * weights[i], i))
                picks[s].setdefault(unit.key, []).append(pos)
                loads[s] += cost

        shards: List[ShardSpec] = []
        for s in range(n_shards):
            if not picks[s]:
                continue
            sub_spec, sub_blob, cis = build_subspec(spec, blob, picks[s],
                                                    n_folds)
            shards.append(ShardSpec(
                sub_spec, sub_blob, cis, loads[s],
                slot=s if weights is not None else None))
        sp.set(candidates=sum(len(s.cis) for s in shards))
    return shards


def launch_packs(spec, blob: np.ndarray, n_slots: int, n_rows: int,
                 n_features: int, n_folds: int,
                 device_weights: Optional[List[float]] = None,
                 budget_bytes: Optional[float] = None,
                 cost_budget: Optional[float] = None) -> List[ShardSpec]:
    """Cost-model-sized launch packs for the partitioned sweep
    (``TMOG_SWEEP_PACK``).

    Two-level packing: first the usual LPT device partition (identical to
    :func:`partition_spec`, including learned-cost pricing under
    ``TMOG_COSTMODEL=1`` and health-weighted slots), then each device
    queue is split into one or more *packs* — each pack one fused XLA
    launch — whenever the queue exceeds the per-launch budgets:

    - **HBM budget** (``budget_bytes``, default ``TMOG_PACK_HBM_MB`` MB,
      analytic): the fused program's transient score block is
      ~``n_rows * n_folds * 4`` bytes per candidate, so at most
      ``budget // per_cand_bytes`` candidates share a launch.
    - **predicted-wall budget** (``cost_budget``, default
      ``TMOG_PACK_COST_BUDGET``, in cost-provider units): with a resolved
      cost provider (learned model or explicit), a queue whose predicted
      cost exceeds the budget is split into ``ceil(cost / budget)``
      LPT-balanced packs.  Unset (0) = no wall cap — the analytic
      fallback packs by HBM alone.

    At the default budgets every queue fits one pack, so the result is
    the *same ``ShardSpec`` objects* ``partition_spec`` returns — the
    packed launcher then runs byte-identical programs.  Every pack
    carries ``slot`` = the device index it was balanced for (multiple
    packs may share a slot; the launcher queues them in order on that
    device).
    """
    from ..utils import env as _env

    if budget_bytes is None:
        budget_bytes = _env.env_float("TMOG_PACK_HBM_MB", 2048.0) * 1e6
    if cost_budget is None:
        cost_budget = _env.env_float("TMOG_PACK_COST_BUDGET", 0.0)
    shards = partition_spec(spec, blob, n_slots, n_rows, n_features,
                            n_folds, device_weights)
    per_cand_bytes = max(float(n_rows) * max(int(n_folds), 1) * 4.0, 1.0)
    cap_cands = max(1, int(budget_bytes // per_cand_bytes))
    provider, _src = _resolve_cost_provider()

    packs: List[ShardSpec] = []
    for pos, sh in enumerate(shards):
        slot = sh.slot if sh.slot is not None else pos
        n_sub = -(-sh.n_candidates // cap_cands)  # ceil: HBM cap
        if provider is not None and cost_budget > 0.0 and sh.cost > 0.0:
            n_sub = max(n_sub, -(-int(math.ceil(sh.cost)) //
                                 max(int(math.ceil(cost_budget)), 1)))
        n_sub = min(max(n_sub, 1), sh.n_candidates)
        if n_sub <= 1:
            # untouched ShardSpec -> byte-identical program when packing
            # changes nothing (the bit-exactness contract)
            packs.append(ShardSpec(sh.spec, sh.blob, sh.cis, sh.cost,
                                   slot=slot))
            continue
        for sub in partition_spec(sh.spec, sh.blob, n_sub, n_rows,
                                  n_features, n_folds):
            # sub.cis index the SHARD's local candidate order; map back
            # to global candidate ids through the parent shard
            gcis = tuple(sh.cis[i] for i in sub.cis)
            packs.append(ShardSpec(sub.spec, sub.blob, gcis, sub.cost,
                                   slot=slot))
    return packs


def rung_packs(spec, blob: np.ndarray, n_rows: int, n_features: int,
               n_folds: int, max_cands: int) -> List[ShardSpec]:
    """Cost-balanced LAUNCH packs for one ASHA rung on a single device.

    The rung scheduler bounds each fused launch by the HBM score-block
    budget (``max_cands`` candidates per launch); this splits the rung's
    spec into ``ceil(C / max_cands)`` LPT-balanced sub-specs the same way
    device shards are built — including learned-cost-model pricing when
    ``TMOG_COSTMODEL=1`` — so successive launches on the one device have
    near-equal predicted walls (the wall prediction the rung records is
    then just their sum)."""
    from ..impl.sweep_fragments import spec_units

    n_cands = sum(len(u.cis)
                  for u in spec_units(spec, n_rows, n_features, n_folds))
    n_packs = max(1, -(-n_cands // max(int(max_cands), 1)))
    return partition_spec(spec, blob, n_packs, n_rows, n_features, n_folds)
