"""Device-mesh utilities — the distributed substrate.

The reference's "communication backend" is Spark shuffle/broadcast/driver RPC
(SURVEY §2.8: reduceByKey in SanityChecker.scala:272, treeAggregate under
Statistics.colStats, MLUtils.kFold).  The TPU-native replacement is a
`jax.sharding.Mesh` with named axes and XLA collectives over ICI:

- axis ``"data"``  — rows sharded across chips (Spark's RDD partitioning
  analog); statistics are psum/all-gather reductions,
- axis ``"model"`` — model-grid candidates sharded across chips (the analog
  of OpValidator's 8-thread JVM pool, OpValidator.scala:373-380); each chip
  trains its slice of the fold x grid sweep with no communication at all.

Multi-host: `jax.distributed.initialize()` extends the same mesh over DCN —
the code below is agnostic to how many processes back the device list.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

#: below this many rows per data shard the collective latency outweighs the
#: per-chip compute saved — the validator routes through the replicated path
#: instead (override with TMOG_MIN_ROWS_PER_SHARD).
DEFAULT_MIN_ROWS_PER_SHARD = 32

#: the mesh the validator sweep currently runs under (see ``use_mesh``)
_ACTIVE_MESH: Optional[Mesh] = None


@contextlib.contextmanager
def use_mesh(mesh: Optional[Mesh]) -> Iterator[Optional[Mesh]]:
    """Scope a mesh for the batched fold x grid kernels.

    ``OpValidator.validate`` wraps the sweep in this; every estimator's
    ``fit_grid_folds`` consults ``active_mesh()`` and shards its candidate
    axis over the mesh ``model`` axis — the estimator API stays unchanged,
    and custom estimators simply run replicated."""
    global _ACTIVE_MESH
    prev = _ACTIVE_MESH
    _ACTIVE_MESH = mesh
    try:
        yield mesh
    finally:
        _ACTIVE_MESH = prev


def active_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH


def model_shards() -> int:
    """Number of shards a batched sweep should pad its candidate axis to."""
    m = _ACTIVE_MESH
    return int(m.shape[MODEL_AXIS]) if m is not None else 1


def data_shards() -> int:
    """Row-shard count of the active mesh (1 without a mesh or data axis)."""
    m = _ACTIVE_MESH
    if m is None or DATA_AXIS not in m.shape:
        return 1
    return int(m.shape[DATA_AXIS])


def model_devices(mesh: Optional[Mesh] = None) -> list:
    """The devices along the ``model`` axis of ``mesh`` (default: the active
    mesh) — one per candidate shard of the partitioned fused sweep.  Taken at
    data-row 0: the fused sweep replicates rows, so each model shard runs on
    exactly one device and any extra ``data``-axis rows are unused by it.
    Falls back to the first local device when no mesh is active."""
    m = mesh if mesh is not None else _ACTIVE_MESH
    if m is None:
        return [jax.local_devices()[0]]
    grid = np.asarray(m.devices)
    ax = list(m.axis_names).index(MODEL_AXIS)
    index = [0] * grid.ndim
    index[ax] = slice(None)
    return list(grid[tuple(index)])


def data_devices(mesh: Optional[Mesh] = None) -> list:
    """The devices along the ``data`` axis of ``mesh`` (default: the active
    mesh) — one per row shard of the streaming transform executor.  Taken at
    model-column 0: the streamed transforms replicate nothing across the
    model axis, so each data shard runs on exactly one device.  Falls back
    to the first local device when no mesh is active."""
    m = mesh if mesh is not None else _ACTIVE_MESH
    if m is None:
        return [jax.local_devices()[0]]
    grid = np.asarray(m.devices)
    ax = list(m.axis_names).index(DATA_AXIS)
    index = [0] * grid.ndim
    index[ax] = slice(None)
    return list(grid[tuple(index)])


def stream_route() -> str:
    """Chunk->device routing policy for the streamed transforms
    (TMOG_STREAM_ROUTE): "roundrobin" (default) dispatches chunk k to data
    device k mod D; "single"/"off" pins every chunk to the default device
    (the legacy path)."""
    from ..utils.env import env_str

    return (env_str("TMOG_STREAM_ROUTE").strip().lower() or "roundrobin")


def stream_shards() -> int:
    """Data-parallel device count for the streamed transform executor.

    Resolution: TMOG_STREAM_ROUTE=single|off forces 1; else an explicit
    TMOG_STREAM_SHARDS wins; else the ``data`` axis of the active mesh (or
    the TMOG_MESH env mesh when none is installed).  Always clamped to the
    local device count, and 1 when nothing requests sharding — the
    single-device path stays bit-identical with TMOG_MESH unset."""
    from ..utils.env import env_int, env_set

    if stream_route() in ("single", "off"):
        return 1
    if env_set("TMOG_STREAM_SHARDS"):
        want = env_int("TMOG_STREAM_SHARDS", 1)
    else:
        m = _ACTIVE_MESH if _ACTIVE_MESH is not None else env_mesh()
        if m is None or DATA_AXIS not in m.shape:
            return 1
        want = int(m.shape[DATA_AXIS])
    # clamp to THIS host's chips: the stream executor only dispatches to
    # addressable devices (identical to jax.devices() single-process)
    return max(1, min(want, len(jax.local_devices())))


def stream_devices() -> list:
    """Dispatch targets for the streamed transforms: the first
    ``stream_shards()`` devices along the data axis of the active/env mesh
    (all local devices when sharding is requested without a mesh).  Returns
    ``[None]`` when unsharded — the executor then uses the default device
    exactly as before."""
    D = stream_shards()
    if D <= 1:
        return [None]
    m = _ACTIVE_MESH if _ACTIVE_MESH is not None else env_mesh()
    devs = data_devices(m) if m is not None else list(jax.local_devices())
    # multi-host: a process-spanning mesh's data axis includes other hosts'
    # chips; this host's stream feeds ONLY its own (chunks it ingested stay
    # resident here — no cross-host row traffic).  Single-process this
    # filter keeps every device, bit-identically.
    devs = local_data_devices(m) if m is not None else devs
    if len(devs) < D:
        devs = list(jax.local_devices())
    devs = devs[:D]
    return devs if len(devs) > 1 else [None]


def auto_mesh() -> Optional[Mesh]:
    """All local devices on the ``model`` axis (the OpValidator default) —
    the TPU replacement for the reference's 8-thread sweep pool
    (OpValidator.scala:373-380).  None on a single device.  LOCAL devices
    only: under ``jax.distributed`` each host runs its own sweep pipeline —
    a process-spanning mesh is ``make_global_mesh``'s job, never an implicit
    default (and XLA:CPU cannot even compile one)."""
    devs = jax.local_devices()
    if len(devs) <= 1:
        return None
    return make_mesh(n_data=1, n_model=len(devs))


def serve_devices(n: Optional[int] = None) -> List[jax.Device]:
    """Devices for the serving replica slots: one per local chip by default,
    overridable via ``TMOG_SERVE_REPLICAS`` (or the explicit ``n``).  Asking
    for more replicas than chips cycles the device list — useful for
    oversubscribing CPU test hosts, harmless on a real mesh."""
    from ..utils.env import env_int

    devs = jax.local_devices()
    if n is None:
        n = env_int("TMOG_SERVE_REPLICAS", len(devs))
    n = max(1, int(n))
    return [devs[i % len(devs)] for i in range(n)]


def serve_chip_index(devices: Sequence[jax.Device]) -> List[int]:
    """Map each serving slot's device to a stable physical-chip ordinal, so
    tenant placement can account chip budgets even when ``serve_devices``
    oversubscribes (several slots cycling one chip share one ordinal).
    Ordinals follow first-appearance order over the slot list — a pure
    function of its input, like everything placement consumes."""
    order: dict = {}
    out: List[int] = []
    for d in devices:
        key = getattr(d, "id", None)
        key = key if key is not None else id(d)
        if key not in order:
            order[key] = len(order)
        out.append(order[key])
    return out


def data_mesh() -> Optional[Mesh]:
    """All local devices on the ``data`` axis — for row-sharded statistics
    passes (SanityChecker / RFF moments + Gram, SURVEY §2.7 axis 1).
    None on a single device (XLA needs no collectives then anyway).  LOCAL
    devices only: per-host partials merge across hosts in the moment domain
    (the ``parallel/stats`` host tier), never as a cross-process XLA mesh."""
    devs = jax.local_devices()
    if len(devs) <= 1:
        return None
    return make_mesh(n_data=len(devs), n_model=1)


def make_mesh(n_data: Optional[int] = None, n_model: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 2-D (data, model) mesh over the available devices.

    With ``n_data=None`` all remaining devices go to the data axis.  A single
    real TPU chip yields a 1x1 mesh — the same program runs unchanged (XLA
    elides the collectives), which is how the reference runs Spark local-mode
    as its test backend (TestSparkContext.scala:50).
    """
    devs = list(devices if devices is not None else jax.local_devices())
    if n_data is None:
        n_data = max(len(devs) // max(n_model, 1), 1)
    n = n_data * n_model
    if n > len(devs):
        raise ValueError(f"mesh {n_data}x{n_model} needs {n} devices, have {len(devs)}")
    grid = np.array(devs[:n]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the data axis; feature dim replicated."""
    return NamedSharding(mesh, P(DATA_AXIS))


def model_sharding(mesh: Mesh) -> NamedSharding:
    """Grid candidates sharded over the model axis."""
    return NamedSharding(mesh, P(MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_candidates(x, fill: float = 0.0) -> Tuple[jax.Array, int]:
    """Pad axis 0 to the active mesh's model-shard count and place sharded.

    Returns (device array sharded over MODEL_AXIS, original length).  With no
    active mesh this is a plain device transfer."""
    import jax.numpy as jnp

    x = np.asarray(x)
    mesh = active_mesh()
    if mesh is None:
        return jnp.asarray(x), x.shape[0]
    padded, n = pad_to_multiple(x, mesh.shape[MODEL_AXIS], axis=0, fill=fill)
    return jax.device_put(jnp.asarray(padded), NamedSharding(mesh, P(MODEL_AXIS))), n


def replicate_input(x) -> jax.Array:
    """Place an array replicated on the active mesh (no-op without one)."""
    import jax.numpy as jnp

    mesh = active_mesh()
    arr = jnp.asarray(x)
    if mesh is None:
        return arr
    return jax.device_put(arr, NamedSharding(mesh, P()))


def pad_to_multiple(x: np.ndarray, multiple: int, axis: int = 0,
                    fill: float = 0.0) -> Tuple[np.ndarray, int]:
    """Pad ``axis`` up to a multiple so shards divide evenly (static shapes).

    Returns (padded, original_length).  Callers mask out padding in
    reductions — the moral equivalent of Spark's uneven final partition.
    """
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_widths = [(0, 0)] * x.ndim
    pad_widths[axis] = (0, rem)
    return np.pad(x, pad_widths, constant_values=fill), n


def shard_rows(x, mesh: Mesh, axis: int = 0,
               fill: float = 0.0) -> Tuple[jax.Array, int]:
    """Pad ``axis`` to a multiple of the mesh's data-shard count and place
    the array row-sharded over DATA_AXIS (other dims replicated).

    Padding rows carry zero sample-weight downstream, so they are numerically
    invisible: weighted reductions add exact zeros and the metric kernels
    already treat zero-weight rows as excluded.  Returns (sharded device
    array, original length)."""
    import jax.numpy as jnp

    x = np.asarray(x)
    n_data = int(mesh.shape[DATA_AXIS])
    padded, n = pad_to_multiple(x, n_data, axis=axis, fill=fill)
    spec = [None] * padded.ndim
    spec[axis] = DATA_AXIS
    return jax.device_put(jnp.asarray(padded), NamedSharding(mesh, P(*spec))), n


# ---------------------------------------------------------------------------
# Collectives with trace-time telemetry.
#
# ``mesh_psum`` / ``mesh_all_gather`` are what the row-sharded fragment
# interpreters call instead of raw ``lax`` collectives: identity when no axis
# name is given (so the same kernel serves the replicated path), and each call
# appends (kind, axis, payload bytes) to the active ``trace_collectives``
# collector *at trace time*.  The launch layer wraps program lowering in the
# collector and replays the recorded set into utils/flops on every call —
# giving per-axis collective accounting without parsing HLO.  Sites inside
# scan/fori_loop bodies are traced once and therefore counted once (the same
# caveat utils/flops documents for FLOPs under lax.scan); vmap batch factors
# are likewise not multiplied into the payload bytes.
# ---------------------------------------------------------------------------

# thread-local: the sweep launcher AOT-compiles per-model-column programs
# concurrently, and each compiling thread must collect only its own trace
_TRACE_TLS = threading.local()


@contextlib.contextmanager
def trace_collectives() -> Iterator[List[Tuple[str, str, int]]]:
    """Collect (kind, axis, bytes) for every mesh collective traced inside."""
    prev = getattr(_TRACE_TLS, "sink", None)
    sink: List[Tuple[str, str, int]] = []
    _TRACE_TLS.sink = sink
    try:
        yield sink
    finally:
        _TRACE_TLS.sink = prev


def _record_collective(kind: str, axis_name: str, x) -> None:
    sink = getattr(_TRACE_TLS, "sink", None)
    if sink is None:
        return
    try:
        nbytes = int(np.prod(x.shape)) * x.dtype.itemsize
    except Exception:
        nbytes = 0
    sink.append((kind, axis_name, nbytes))


def record_trace_event(kind: str, tag: str, value: int) -> None:
    """Append a non-collective trace event to the active collector.

    Rides the same sink as the collectives so launch layers that already
    capture/replay the trace pick these up for free.  Used by the tree
    grower to report histogram-subtraction savings (kind
    ``"hist_subtracted"``, value = avoided FLOPs per traced level) —
    utils/flops routes that kind into a dedicated bucket instead of the
    per-axis collective traffic."""
    sink = getattr(_TRACE_TLS, "sink", None)
    if sink is None:
        return
    sink.append((kind, tag, int(value)))


def mesh_psum(x, axis_name: Optional[str]):
    """``lax.psum`` over ``axis_name``; identity when ``axis_name`` is None.

    The single entry point the fused-fragment kernels use for cross-row
    reductions: Gram/normal-equation blocks, gradient/hessian histograms,
    per-fold accumulators.  Calling with None keeps the replicated path
    byte-for-byte identical to the pre-row-sharding kernels."""
    if axis_name is None:
        return x
    from jax import lax

    _record_collective("psum", axis_name, x)
    return lax.psum(x, axis_name)


def mesh_all_gather(x, axis_name: Optional[str], axis: int = 0):
    """Tiled ``lax.all_gather`` over ``axis_name``; identity when None.

    Used where a reduction cannot be expressed as a sum — the rank/sort-based
    metrics (AuROC/AuPR) need the global row order reassembled."""
    if axis_name is None:
        return x
    from jax import lax

    _record_collective("all_gather", axis_name, x)
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


# ---------------------------------------------------------------------------
# Multi-host topology — process-spanning meshes and per-host row ranges.
#
# ``jax.distributed.initialize`` (parallel/distributed.py) makes
# ``jax.devices()`` span every host; the helpers below carve that global pool
# into a host-major (data, model) mesh and assign each host its contiguous
# slice of the global row axis.  Everything degrades to the single-host
# behavior when ``host_count() == 1``: ``host_rows(n)`` is ``(0, n)``,
# ``make_global_mesh`` is ``make_mesh``, and no call below touches
# ``jax.distributed`` state — the one-host path stays bit-identical.
# ---------------------------------------------------------------------------


def host_count() -> int:
    """Number of hosts (processes) in the cluster.

    An explicit ``TMOG_HOSTS`` wins (lets single-process tests and the
    scale harness exercise the range math without ``jax.distributed``);
    otherwise ``jax.process_count()`` (1 when not distributed)."""
    from ..utils.env import env_int, env_set

    if env_set("TMOG_HOSTS"):
        return max(1, env_int("TMOG_HOSTS", 1))
    try:
        return max(1, int(jax.process_count()))
    except Exception:
        return 1


def host_index() -> int:
    """This process's host rank in ``[0, host_count())``.

    ``TMOG_HOST_INDEX`` wins; otherwise ``jax.process_index()`` (0 when not
    distributed)."""
    from ..utils.env import env_int, env_set

    if env_set("TMOG_HOST_INDEX"):
        return max(0, env_int("TMOG_HOST_INDEX", 0))
    try:
        return max(0, int(jax.process_index()))
    except Exception:
        return 0


def host_rows(n_rows: int, index: Optional[int] = None,
              count: Optional[int] = None) -> Tuple[int, int]:
    """Contiguous global row range ``[lo, hi)`` owned by one host.

    Ranges are disjoint, covering, and within one row of balanced: the
    first ``n_rows % count`` hosts carry the remainder row each.  A host
    past the data (``count > n_rows``) gets an empty range — legal, its
    stream simply runs zero chunks.  With one host this is ``(0, n_rows)``,
    so the single-host path sees no change at all."""
    H = max(1, int(count if count is not None else host_count()))
    h = int(index if index is not None else host_index())
    if not 0 <= h < H:
        raise ValueError(f"host_index {h} out of range for {H} hosts")
    n = max(0, int(n_rows))
    base, extra = divmod(n, H)
    lo = h * base + min(h, extra)
    hi = lo + base + (1 if h < extra else 0)
    return lo, hi


def make_global_mesh(n_hosts: Optional[int] = None,
                     n_data: Optional[int] = None,
                     n_model: int = 1) -> Mesh:
    """Build a host-major (data, model) mesh spanning ``n_hosts`` processes.

    Devices are grouped by owning process and laid out host-major along the
    data axis, so host ``h``'s local chips own the contiguous block of row
    shards ``[h * n_data/n_hosts, (h+1) * n_data/n_hosts)`` — matching the
    ``host_rows`` ingestion ranges, which keeps every streamed chunk resident
    on the host that read it.  ``mesh_psum``/``mesh_all_gather`` compose
    unchanged (same axis names; XLA routes the cross-host hops over DCN).

    With ``n_data=None`` each host contributes all its local chips to the
    data axis.  On one process this degrades exactly to ``make_mesh``."""
    H = max(1, int(n_hosts) if n_hosts is not None else host_count())
    by_proc: dict = {}
    for d in jax.devices():
        by_proc.setdefault(int(getattr(d, "process_index", 0)), []).append(d)
    procs = sorted(by_proc)
    if H > len(procs):
        raise ValueError(
            f"global mesh over {H} hosts needs {H} processes, "
            f"have {len(procs)} (did jax.distributed initialize?)")
    procs = procs[:H]
    n_model = max(1, int(n_model))
    if n_data is None:
        per_host = max(min(len(by_proc[p]) for p in procs) // n_model, 1)
        n_data = per_host * H
    n_data = int(n_data)
    if n_data % H:
        raise ValueError(f"data axis {n_data} not divisible by {H} hosts")
    per_host = n_data // H
    need = per_host * n_model
    rows: List[jax.Device] = []
    for p in procs:
        local = by_proc[p]
        if need > len(local):
            raise ValueError(
                f"host {p} holds {len(local)} devices, mesh block needs {need}")
        rows.extend(local[:need])
    grid = np.array(rows).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def local_data_devices(mesh: Optional[Mesh] = None) -> list:
    """Data-axis devices of ``mesh`` owned by THIS process.

    The per-host stream executor dispatches only to these, so chunks read by
    a host stay resident on that host's chips.  Falls back to the full
    data-axis list when the mesh is single-process (every device is local)."""
    devs = data_devices(mesh)
    try:
        pid = int(jax.process_index())
    except Exception:
        pid = 0
    local = [d for d in devs if int(getattr(d, "process_index", 0)) == pid]
    return local or devs


# ---------------------------------------------------------------------------
# Mesh selection and row-sharding profitability policy.
# ---------------------------------------------------------------------------


def env_mesh() -> Optional[Mesh]:
    """Mesh requested via TMOG_MESH ("DxM", e.g. "2x4"; bare "8" means 1x8).

    Returns None when the variable is unset/empty or the device pool cannot
    satisfy the request (so CI matrix entries degrade gracefully on smaller
    hosts instead of erroring)."""
    from ..utils.env import env_str

    spec = env_str("TMOG_MESH").lower()
    if not spec:
        return None
    try:
        if "x" in spec:
            d_s, m_s = spec.split("x", 1)
            n_data, n_model = int(d_s), int(m_s)
        else:
            n_data, n_model = 1, int(spec)
        if n_data < 1 or n_model < 1:
            return None
        return make_mesh(n_data=n_data, n_model=n_model)
    except (ValueError, RuntimeError):
        return None


def min_rows_per_shard() -> int:
    """Fewest rows per data shard worth the collective round-trips."""
    from ..utils.env import env_int

    return max(env_int("TMOG_MIN_ROWS_PER_SHARD",
                       DEFAULT_MIN_ROWS_PER_SHARD), 1)


def rowshard_viable(n_rows: int, n_data: int) -> bool:
    """Whether a row-sharded launch over ``n_data`` shards is profitable.

    The validator falls back to the replicated sweep (and records the reason
    in ``ops.sweep.run_stats()``) when this is False."""
    return n_data > 1 and n_rows >= n_data * min_rows_per_shard()
