"""Device-mesh utilities — the distributed substrate.

The reference's "communication backend" is Spark shuffle/broadcast/driver RPC
(SURVEY §2.8: reduceByKey in SanityChecker.scala:272, treeAggregate under
Statistics.colStats, MLUtils.kFold).  The TPU-native replacement is a
`jax.sharding.Mesh` with named axes and XLA collectives over ICI:

- axis ``"data"``  — rows sharded across chips (Spark's RDD partitioning
  analog); statistics are psum/all-gather reductions,
- axis ``"model"`` — model-grid candidates sharded across chips (the analog
  of OpValidator's 8-thread JVM pool, OpValidator.scala:373-380); each chip
  trains its slice of the fold x grid sweep with no communication at all.

Multi-host: `jax.distributed.initialize()` extends the same mesh over DCN —
the code below is agnostic to how many processes back the device list.
"""
from __future__ import annotations

import contextlib
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

#: the mesh the validator sweep currently runs under (see ``use_mesh``)
_ACTIVE_MESH: Optional[Mesh] = None


@contextlib.contextmanager
def use_mesh(mesh: Optional[Mesh]) -> Iterator[Optional[Mesh]]:
    """Scope a mesh for the batched fold x grid kernels.

    ``OpValidator.validate`` wraps the sweep in this; every estimator's
    ``fit_grid_folds`` consults ``active_mesh()`` and shards its candidate
    axis over the mesh ``model`` axis — the estimator API stays unchanged,
    and custom estimators simply run replicated."""
    global _ACTIVE_MESH
    prev = _ACTIVE_MESH
    _ACTIVE_MESH = mesh
    try:
        yield mesh
    finally:
        _ACTIVE_MESH = prev


def active_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH


def model_shards() -> int:
    """Number of shards a batched sweep should pad its candidate axis to."""
    m = _ACTIVE_MESH
    return int(m.shape[MODEL_AXIS]) if m is not None else 1


def model_devices(mesh: Optional[Mesh] = None) -> list:
    """The devices along the ``model`` axis of ``mesh`` (default: the active
    mesh) — one per candidate shard of the partitioned fused sweep.  Taken at
    data-row 0: the fused sweep replicates rows, so each model shard runs on
    exactly one device and any extra ``data``-axis rows are unused by it.
    Falls back to the first local device when no mesh is active."""
    m = mesh if mesh is not None else _ACTIVE_MESH
    if m is None:
        return [jax.devices()[0]]
    grid = np.asarray(m.devices)
    ax = list(m.axis_names).index(MODEL_AXIS)
    index = [0] * grid.ndim
    index[ax] = slice(None)
    return list(grid[tuple(index)])


def auto_mesh() -> Optional[Mesh]:
    """All local devices on the ``model`` axis (the OpValidator default) —
    the TPU replacement for the reference's 8-thread sweep pool
    (OpValidator.scala:373-380).  None on a single device."""
    devs = jax.devices()
    if len(devs) <= 1:
        return None
    return make_mesh(n_data=1, n_model=len(devs))


def data_mesh() -> Optional[Mesh]:
    """All local devices on the ``data`` axis — for row-sharded statistics
    passes (SanityChecker / RFF moments + Gram, SURVEY §2.7 axis 1).
    None on a single device (XLA needs no collectives then anyway)."""
    devs = jax.devices()
    if len(devs) <= 1:
        return None
    return make_mesh(n_data=len(devs), n_model=1)


def make_mesh(n_data: Optional[int] = None, n_model: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 2-D (data, model) mesh over the available devices.

    With ``n_data=None`` all remaining devices go to the data axis.  A single
    real TPU chip yields a 1x1 mesh — the same program runs unchanged (XLA
    elides the collectives), which is how the reference runs Spark local-mode
    as its test backend (TestSparkContext.scala:50).
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = max(len(devs) // max(n_model, 1), 1)
    n = n_data * n_model
    if n > len(devs):
        raise ValueError(f"mesh {n_data}x{n_model} needs {n} devices, have {len(devs)}")
    grid = np.array(devs[:n]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the data axis; feature dim replicated."""
    return NamedSharding(mesh, P(DATA_AXIS))


def model_sharding(mesh: Mesh) -> NamedSharding:
    """Grid candidates sharded over the model axis."""
    return NamedSharding(mesh, P(MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_candidates(x, fill: float = 0.0) -> Tuple[jax.Array, int]:
    """Pad axis 0 to the active mesh's model-shard count and place sharded.

    Returns (device array sharded over MODEL_AXIS, original length).  With no
    active mesh this is a plain device transfer."""
    import jax.numpy as jnp

    x = np.asarray(x)
    mesh = active_mesh()
    if mesh is None:
        return jnp.asarray(x), x.shape[0]
    padded, n = pad_to_multiple(x, mesh.shape[MODEL_AXIS], axis=0, fill=fill)
    return jax.device_put(jnp.asarray(padded), NamedSharding(mesh, P(MODEL_AXIS))), n


def replicate_input(x) -> jax.Array:
    """Place an array replicated on the active mesh (no-op without one)."""
    import jax.numpy as jnp

    mesh = active_mesh()
    arr = jnp.asarray(x)
    if mesh is None:
        return arr
    return jax.device_put(arr, NamedSharding(mesh, P()))


def pad_to_multiple(x: np.ndarray, multiple: int, axis: int = 0,
                    fill: float = 0.0) -> Tuple[np.ndarray, int]:
    """Pad ``axis`` up to a multiple so shards divide evenly (static shapes).

    Returns (padded, original_length).  Callers mask out padding in
    reductions — the moral equivalent of Spark's uneven final partition.
    """
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_widths = [(0, 0)] * x.ndim
    pad_widths[axis] = (0, rem)
    return np.pad(x, pad_widths, constant_values=fill), n
