"""Multi-host distributed substrate — jax.distributed over DCN.

Reference ground truth (SURVEY §2.8): the reference's communication backend
is Spark shuffle/broadcast/driver-RPC across executor JVMs.  The TPU-native
replacement keeps ONE program shape at every scale:

- single chip: a 1x1 mesh, collectives elided by XLA,
- one host, many chips: a (data, model) mesh over ICI,
- many hosts: ``jax.distributed.initialize`` connects the processes over
  DCN.  Per-host pipelines (``mesh.make_mesh`` and friends) stay LOCAL —
  each host ingests only its ``mesh.host_rows`` range and runs its own
  device-resident stream/sweep over its own chips; statistics cross hosts
  in the tiny moment domain (``parallel/stats`` host tier), never as rows.
  A deliberately process-spanning mesh is ``mesh.make_global_mesh``'s job
  (host-major data axis, aligned with the ingestion ranges).

Process topology comes from explicit args or the standard environment
(``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``,
or their ``TMOG_*`` aliases), so an OpApp launched by any scheduler
(GKE/slurm-style) joins the cluster with ``--distributed`` alone.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import jax

_INITIALIZED = False


@dataclass
class DistributedInfo:
    coordinator: str
    num_processes: int
    process_id: int
    global_devices: int
    local_devices: int


def _env(*names: str) -> Optional[str]:
    for n in names:
        v = os.environ.get(n)
        if v:
            return v
    return None


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None
                           ) -> DistributedInfo:
    """Join (or form) the multi-host cluster; idempotent.

    After this returns, ``jax.devices()`` spans all hosts,
    ``mesh.host_count()``/``host_index()`` report the topology, the readers
    shard ingestion by ``mesh.host_rows``, and the stats tier merges
    per-host moments globally; the per-host pipelines themselves keep
    running on ``jax.local_devices()`` unchanged.
    """
    global _INITIALIZED
    coordinator_address = coordinator_address or _env(
        "TMOG_COORDINATOR", "JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes if num_processes is not None else int(
        _env("TMOG_NUM_PROCESSES", "JAX_NUM_PROCESSES") or 1)
    process_id = process_id if process_id is not None else int(
        _env("TMOG_PROCESS_ID", "JAX_PROCESS_ID") or 0)
    if num_processes > 1 and not coordinator_address:
        raise ValueError("multi-process run needs a coordinator address "
                         "(--distributed host:port or TMOG_COORDINATOR)")
    if not _INITIALIZED and num_processes > 1:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        _INITIALIZED = True
    return DistributedInfo(
        coordinator=coordinator_address or "local",
        num_processes=num_processes, process_id=process_id,
        global_devices=len(jax.devices()),
        local_devices=len(jax.local_devices()))


def is_distributed() -> bool:
    return _INITIALIZED


def shutdown() -> None:
    global _INITIALIZED
    if _INITIALIZED:
        jax.distributed.shutdown()
        _INITIALIZED = False
