"""Row-sharded streaming statistics — SURVEY §2.7 axis 1 and §5.7.

The reference computes column moments and correlations with Spark
``Statistics.colStats`` / ``Statistics.corr`` — treeAggregate reductions over
executor row partitions (SanityChecker.scala:406-470).  The O(p²)
feature×feature correlation is its "long axis" (SURVEY §5.7).  TPU-native
formulation:

- rows arrive in CHUNKS (the dataset may exceed HBM: 10M x 500 f32 = 20 GB
  vs 16 GB on a v5e chip); each chunk is placed sharded over the mesh
  ``data`` axis and reduced on device — XLA inserts the psum collectives
  from the sharding annotations (the scaling-book recipe),
- pass 1 accumulates count / sum / sum-of-squares / min / max per column,
- pass 2 accumulates the CENTERED Gram Z^T Z (+ Z^T z_y) — one MXU matmul
  per chunk — from which the full p x p Pearson matrix and the label
  correlations fall out.  Centering first keeps f32 accumulation accurate
  (raw second moments over 10M rows would not be),
- accumulators live on device replicated; one tiny d2h at finalize.

Spearman needs a GLOBAL rank transform first (Spark Statistics.corr
"spearman" sorts each column cluster-wide, SanityChecker.scala:406-466);
here ``rank_transform`` computes per-column midranks on device in column
blocks (sort + two searchsorteds — ties averaged exactly like
utils/stats._rank_data), then the SAME streaming Pearson passes run over
the ranks, whose mean is exactly (n+1)/2.  Sampled Spearman stays available
via utils/stats.correlations_with_label.
"""
from __future__ import annotations

from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import DATA_AXIS
from ..utils.stats import ColStats


def _data_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS))


@jax.jit
def _moments_step(carry, X, m):
    """carry: (n, s1, s2, mn, mx); X f32[rows, d] (sharded over data), m
    f32[rows] validity mask (0 for padding rows)."""
    n, s1, s2, mn, mx = carry
    Xm = X * m[:, None]
    n = n + m.sum()
    s1 = s1 + Xm.sum(axis=0)
    s2 = s2 + (X * Xm).sum(axis=0)
    mn = jnp.minimum(mn, jnp.where(m[:, None] > 0, X, jnp.inf).min(axis=0))
    mx = jnp.maximum(mx, jnp.where(m[:, None] > 0, X, -jnp.inf).max(axis=0))
    return n, s1, s2, mn, mx


@jax.jit
def _gram_step(carry, X, yv, m, mean, y_mean):
    """carry: (G [d,d], gy [d], yy, n); accumulates the centered Gram."""
    G, gy, yy, n = carry
    Z = (X - mean[None, :]) * m[:, None]
    zy = (yv - y_mean) * m
    G = G + Z.T @ Z
    gy = gy + Z.T @ zy
    yy = yy + (zy * zy).sum()
    n = n + m.sum()
    return G, gy, yy, n


class DataShardedStats:
    """Two-pass streaming moments + correlations over row chunks.

    ``mesh=None`` runs single-device (same code path; XLA elides the
    collectives) — the Spark local-mode analog.  Chunks may be any row
    count; they are padded to the data-shard multiple with masked rows.
    """

    def __init__(self, d: int, mesh=None):
        self.d = d
        self.mesh = mesh
        self.n_shards = int(mesh.shape[DATA_AXIS]) if mesh is not None else 1

    def _place(self, arr: np.ndarray):
        if self.mesh is None:
            return jnp.asarray(arr)
        return jax.device_put(jnp.asarray(arr), _data_sharding(self.mesh))

    def _chunks_masked(self, chunks: Iterable[np.ndarray]
                       ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        for X in chunks:
            X = np.ascontiguousarray(np.asarray(X, np.float32))
            rows = X.shape[0]
            pad = (-rows) % self.n_shards
            m = np.ones(rows, np.float32)
            if pad:
                X = np.concatenate([X, np.zeros((pad, X.shape[1]), np.float32)])
                m = np.concatenate([m, np.zeros(pad, np.float32)])
            yield X, m

    # ---- pass 1 ------------------------------------------------------------
    def moments(self, chunks: Iterable[np.ndarray]) -> ColStats:
        d = self.d
        carry = (jnp.zeros(()), jnp.zeros(d), jnp.zeros(d),
                 jnp.full(d, jnp.inf), jnp.full(d, -jnp.inf))
        for X, m in self._chunks_masked(chunks):
            carry = _moments_step(carry, self._place(X), self._place(m))
        n, s1, s2, mn, mx = (np.asarray(c, np.float64) for c in carry)
        n = float(n)
        mean = s1 / max(n, 1.0)
        var = np.maximum(s2 / max(n, 1.0) - mean * mean, 0.0) * (
            n / max(n - 1.0, 1.0))  # sample variance (Spark colStats)
        return ColStats(count=int(n), mean=mean, variance=var, min=mn, max=mx)

    # ---- pass 2 ------------------------------------------------------------
    def correlations_from(self, chunks_factory, mean: np.ndarray, y_mean: float,
                          with_corr_matrix: bool = True
                          ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """``chunks_factory()`` yields (X_chunk [rows, d], y_chunk [rows])
        pairs.  Returns (corr_with_label [d], corr_matrix [d,d] | None)."""
        d = self.d
        meand = jnp.asarray(mean, jnp.float32)
        ymd = jnp.asarray(np.float32(y_mean))
        carry = (jnp.zeros((d, d)), jnp.zeros(d), jnp.zeros(()), jnp.zeros(()))
        for X, y in chunks_factory():
            X = np.ascontiguousarray(np.asarray(X, np.float32))
            y = np.asarray(y, np.float32)
            rows = X.shape[0]
            pad = (-rows) % self.n_shards
            m = np.ones(rows, np.float32)
            if pad:
                X = np.concatenate([X, np.zeros((pad, d), np.float32)])
                y = np.concatenate([y, np.zeros(pad, np.float32)])
                m = np.concatenate([m, np.zeros(pad, np.float32)])
            carry = _gram_step(carry, self._place(X), self._place(y),
                               self._place(m), meand, ymd)
        G, gy, yy, n = (np.asarray(c, np.float64) for c in carry)
        diag = np.diag(G).copy()
        zero = diag <= 0.0
        denom = np.sqrt(np.maximum(diag, 1e-300))
        with np.errstate(invalid="ignore", divide="ignore"):
            corr_label = gy / (denom * np.sqrt(max(float(yy), 1e-300)))
        corr_label[zero] = np.nan
        corr_matrix = None
        if with_corr_matrix:
            corr_matrix = G / np.outer(denom, denom)
            np.fill_diagonal(corr_matrix, 1.0)
            corr_matrix[zero, :] = np.nan
            corr_matrix[:, zero] = np.nan
        return corr_label, corr_matrix


def chunked(X: np.ndarray, y: Optional[np.ndarray] = None,
            chunk_rows: int = 1 << 18):
    """Row-chunk an in-memory array (factory usable for both passes)."""
    n = X.shape[0]

    def gen_x():
        for lo in range(0, n, chunk_rows):
            yield X[lo:lo + chunk_rows]

    if y is None:
        return gen_x

    def gen_xy():
        for lo in range(0, n, chunk_rows):
            yield X[lo:lo + chunk_rows], y[lo:lo + chunk_rows]

    return gen_xy


@jax.jit
def _fused_stats_step(carry, X, yv, m):
    """ONE-pass moments + mean-centered Gram via Chan's pairwise merge.

    carry: (n, mean[d], y_mean, mn, mx, G[d,d], gy[d], yy) where G/gy/yy are
    centered at the CARRY means.  Each chunk is centered at its OWN means
    and merged with the exact pairwise-update cross terms
    (f = n0*nc/(n0+nc); G += Gc + f dx dx^T; gy += gyc + f dx dy;
    yy += yyc + f dy^2), so no large-offset cancellation ever enters the
    f32 accumulators — a constant-center scheme would cancel catastrophically
    on row-ordered data whose mean drifts.  ONE pass means each chunk
    uploads once: on a tunneled backend the second upload of the matrix was
    the single largest cost of the two-pass scheme (round-5 measurement:
    ~63 MB/s real upload bandwidth on incompressible data).
    """
    n0, mean0, ym0, mn, mx, G, gy, yy = carry
    nc = m.sum()
    ncs = jnp.maximum(nc, 1.0)
    mc = (X * m[:, None]).sum(axis=0) / ncs
    yc = (yv * m).sum() / ncs
    Z = (X - mc[None, :]) * m[:, None]
    zy = (yv - yc) * m
    Gc = Z.T @ Z
    gyc = Z.T @ zy
    yyc = (zy * zy).sum()
    nt = n0 + nc
    f = jnp.where(nt > 0, n0 * nc / jnp.maximum(nt, 1.0), 0.0)
    dx = mc - mean0
    dy = yc - ym0
    G = G + Gc + f * jnp.outer(dx, dx)
    gy = gy + gyc + f * dx * dy
    yy = yy + yyc + f * dy * dy
    w = nc / jnp.maximum(nt, 1.0)
    mean = mean0 + dx * w
    ym = ym0 + dy * w
    mn = jnp.minimum(mn, jnp.where(m[:, None] > 0, X, jnp.inf).min(axis=0))
    mx = jnp.maximum(mx, jnp.where(m[:, None] > 0, X, -jnp.inf).max(axis=0))
    return nt, mean, ym, mn, mx, G, gy, yy


@jax.jit
def _chan_moments_step(carry, X, m):
    """One Chan pairwise-merge step of streaming column moments.

    carry: (n, mean[d], M2[d]) with M2 the CENTERED sum of squares.  The
    chunk is centered at its OWN mean and merged with the exact pairwise
    cross term (the _fused_stats_step recipe minus the Gram), so no raw
    second moments enter the f32 accumulator.  m masks padding rows."""
    n0, mean0, M2 = carry
    nc = m.sum()
    ncs = jnp.maximum(nc, 1.0)
    mc = (X * m[:, None]).sum(axis=0) / ncs
    Z = (X - mc[None, :]) * m[:, None]
    M2c = (Z * Z).sum(axis=0)
    nt = n0 + nc
    f = jnp.where(nt > 0, n0 * nc / jnp.maximum(nt, 1.0), 0.0)
    dx = mc - mean0
    M2 = M2 + M2c + f * dx * dx
    mean = mean0 + dx * (nc / jnp.maximum(nt, 1.0))
    return nt, mean, M2


def _merge_moment_carries(carries):
    """Chan-merge per-device (n, mean, M2) partials host-side in f64 — the
    cross-device half of the reduction (ROADMAP item 4's per-host merge
    pattern, applied across the stream devices of one host)."""
    n_t: float = 0.0
    mean_t = M2_t = None
    for c in carries:
        n_c, mean_c, M2_c = (np.asarray(x, np.float64) for x in c)
        n_c = float(n_c)
        if n_c <= 0:
            continue
        if mean_t is None:
            n_t, mean_t, M2_t = n_c, mean_c, M2_c
            continue
        nt = n_t + n_c
        dx = mean_c - mean_t
        M2_t = M2_t + M2_c + (n_t * n_c / nt) * dx * dx
        mean_t = mean_t + dx * (n_c / nt)
        n_t = nt
    return n_t, mean_t, M2_t


def sharded_column_moments(X: np.ndarray, chunk_rows: int = 1 << 18,
                           devices: Optional[list] = None
                           ) -> Tuple[float, np.ndarray, np.ndarray]:
    """Column mean and POPULATION std of ``X [n, d]`` via per-device
    round-robin Chan partials.

    Chunk i accumulates into device i-mod-D's carry, so each device runs an
    independent async accumulation pipeline (no per-chunk lockstep
    collective, unlike the mesh-placed ``DataShardedStats``), and the D
    partial carries merge exactly at the end.  This is what the streamed
    scaler fit reduces through when the transform stream is sharded — fit
    and transform ride the same devices.  Returns ``(count, mean, std)``
    f64; ``devices=None``/single runs the identical math on the default
    device."""
    X = np.asarray(X)
    n = X.shape[0]
    d = X.shape[1] if X.ndim > 1 else 1
    X = X.reshape(n, d)
    devices = list(devices) if devices else [None]
    D = len(devices)
    carries: list = [None] * D
    for k, lo in enumerate(range(0, n, chunk_rows)):
        chunk = np.ascontiguousarray(X[lo:lo + chunk_rows], np.float32)
        rows = chunk.shape[0]
        m = np.ones(rows, np.float32)
        if rows < chunk_rows:  # constant chunk shape: one compile per device
            chunk = np.concatenate(
                [chunk, np.zeros((chunk_rows - rows, d), np.float32)])
            m = np.concatenate([m, np.zeros(chunk_rows - rows, np.float32)])
        di = k % D
        dev = devices[di]
        if carries[di] is None:
            z = (jnp.zeros(()), jnp.zeros(d), jnp.zeros(d))
            carries[di] = jax.device_put(z, dev) if dev is not None else z
        xa = jax.device_put(chunk, dev) if dev is not None \
            else jnp.asarray(chunk)
        ma = jax.device_put(m, dev) if dev is not None else jnp.asarray(m)
        carries[di] = _chan_moments_step(carries[di], xa, ma)
    n_t, mean, M2 = _merge_moment_carries(
        [c for c in carries if c is not None])
    if not n_t or mean is None:
        z = np.zeros(d)
        return 0.0, z, z.copy()
    return n_t, mean, np.sqrt(np.maximum(M2, 0.0) / n_t)


@jax.jit
def _midrank_cols(Xb):
    """Per-column average-tie midranks (1-based): f32[n, k] -> f32[n, k]."""

    def one(col):
        order = jnp.argsort(col)
        ss = col[order]
        lo = jnp.searchsorted(ss, ss, side="left")
        hi = jnp.searchsorted(ss, ss, side="right")
        mid = (lo + hi + 1).astype(jnp.float32) * 0.5
        return jnp.zeros_like(mid).at[order].set(mid)

    return jax.vmap(one, in_axes=1, out_axes=1)(Xb)


def rank_transform(X: np.ndarray, block_cols: int = 128) -> np.ndarray:
    """Global average-tie ranks per column, computed on device in column
    blocks (the Spearman prep; parity with utils/stats._rank_data)."""
    X = np.asarray(X, np.float32)
    if X.ndim == 1:
        return rank_transform(X[:, None], block_cols)[:, 0]
    n, d = X.shape
    out = np.empty((n, d), np.float32)
    for lo in range(0, d, block_cols):
        blk = np.ascontiguousarray(X[:, lo:lo + block_cols])
        out[:, lo:lo + block_cols] = np.asarray(_midrank_cols(jnp.asarray(blk)))
    return out


def fused_moments_and_correlations(chunks_factory, d: int, mesh=None,
                                   with_corr_matrix: bool = True
                                   ) -> Tuple[ColStats, np.ndarray,
                                              Optional[np.ndarray]]:
    """ONE streaming pass: column moments AND label/feature correlations.

    ``chunks_factory()`` yields (X_chunk [rows, d], y_chunk [rows]) pairs —
    each chunk uploads ONCE (the two-pass scheme re-uploaded the whole
    matrix for the Gram pass; uploads dominate on a tunneled link).  Gram,
    mean, and variance accumulate with Chan's numerically-stable pairwise
    merge (see _fused_stats_step); variance falls out of the centered
    Gram's diagonal.
    """
    acc = DataShardedStats(d, mesh=mesh)
    carry = None
    for X, y in chunks_factory():
        X = np.ascontiguousarray(np.asarray(X, np.float32))
        y = np.asarray(y, np.float32)
        rows = X.shape[0]
        pad = (-rows) % acc.n_shards
        m = np.ones(rows, np.float32)
        if pad:
            X = np.concatenate([X, np.zeros((pad, d), np.float32)])
            y = np.concatenate([y, np.zeros(pad, np.float32)])
            m = np.concatenate([m, np.zeros(pad, np.float32)])
        if carry is None:
            carry = (jnp.zeros(()), jnp.zeros(d), jnp.zeros(()),
                     jnp.full(d, jnp.inf), jnp.full(d, -jnp.inf),
                     jnp.zeros((d, d)), jnp.zeros(d), jnp.zeros(()))
        carry = _fused_stats_step(carry, acc._place(X), acc._place(y),
                                  acc._place(m))
    if carry is None:
        z = np.zeros(d)
        return ColStats(0, z, z.copy(), z.copy(), z.copy()), \
            np.full(d, np.nan), None
    n_, mean, _ym, mn, mx, G, gy, yy = (np.asarray(c, np.float64)
                                        for c in carry)
    n = float(n_)
    yy = float(yy)
    # sample variance straight off the centered Gram's diagonal
    var = np.maximum(np.diag(G), 0.0) / max(n - 1.0, 1.0)
    stats = ColStats(count=int(n), mean=mean, variance=var, min=mn, max=mx)
    diag = np.diag(G).copy()
    zero = diag <= 0.0
    denom = np.sqrt(np.maximum(diag, 1e-300))
    with np.errstate(invalid="ignore", divide="ignore"):
        corr_label = gy / (denom * np.sqrt(max(yy, 1e-300)))
    corr_label[zero] = np.nan
    corr_matrix = None
    if with_corr_matrix:
        corr_matrix = G / np.outer(denom, denom)
        np.fill_diagonal(corr_matrix, 1.0)
        corr_matrix[zero, :] = np.nan
        corr_matrix[:, zero] = np.nan
    return stats, corr_label, corr_matrix


def sharded_correlations(X: np.ndarray, y: np.ndarray, mesh=None,
                         with_corr_matrix: bool = True,
                         chunk_rows: int = 1 << 18, method: str = "pearson"
                         ) -> Tuple[ColStats, np.ndarray, Optional[np.ndarray]]:
    """Drop-in large-data correlation path for SanityChecker: two sharded
    streaming passes over row chunks.  ``method`` "spearman" rank-transforms
    every column on device first (one extra [n, d] f32 materialization) and
    streams Pearson over the ranks; column stats are always raw-space.
    Returns (col_stats, corr_with_label, corr_matrix|None) matching
    utils/stats.correlations_with_label."""
    n = X.shape[0]
    acc = DataShardedStats(X.shape[1], mesh=mesh)
    stats = acc.moments(chunked(X, chunk_rows=chunk_rows)())
    if method == "spearman":
        Xc = rank_transform(X)
        yc = rank_transform(np.asarray(y, np.float32))
        mean_c = np.full(X.shape[1], (n + 1) / 2.0)  # midrank mean, exact
        y_mean = (n + 1) / 2.0
    else:
        Xc, yc = X, y
        mean_c = stats.mean
        y64 = np.asarray(y, np.float64)
        y_mean = float(y64.mean()) if len(y64) else 0.0
    corr_label, corr_matrix = acc.correlations_from(
        chunked(Xc, yc, chunk_rows=chunk_rows), mean_c, y_mean,
        with_corr_matrix=with_corr_matrix)
    return stats, corr_label, corr_matrix
