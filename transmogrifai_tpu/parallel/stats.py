"""Row-sharded streaming statistics — SURVEY §2.7 axis 1 and §5.7.

The reference computes column moments and correlations with Spark
``Statistics.colStats`` / ``Statistics.corr`` — treeAggregate reductions over
executor row partitions (SanityChecker.scala:406-470).  The O(p²)
feature×feature correlation is its "long axis" (SURVEY §5.7).  TPU-native
formulation:

- rows arrive in CHUNKS (the dataset may exceed HBM: 10M x 500 f32 = 20 GB
  vs 16 GB on a v5e chip); each chunk is placed sharded over the mesh
  ``data`` axis and reduced on device — XLA inserts the psum collectives
  from the sharding annotations (the scaling-book recipe),
- pass 1 accumulates count / sum / sum-of-squares / min / max per column,
- pass 2 accumulates the CENTERED Gram Z^T Z (+ Z^T z_y) — one MXU matmul
  per chunk — from which the full p x p Pearson matrix and the label
  correlations fall out.  Centering first keeps f32 accumulation accurate
  (raw second moments over 10M rows would not be),
- accumulators live on device replicated; one tiny d2h at finalize.

Spearman needs a GLOBAL rank transform first (Spark Statistics.corr
"spearman" sorts each column cluster-wide, SanityChecker.scala:406-466);
here ``rank_transform`` computes per-column midranks on device in column
blocks (sort + two searchsorteds — ties averaged exactly like
utils/stats._rank_data), then the SAME streaming Pearson passes run over
the ranks, whose mean is exactly (n+1)/2.  Sampled Spearman stays available
via utils/stats.correlations_with_label.
"""
from __future__ import annotations

from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import DATA_AXIS
from ..utils.stats import ColStats


def _data_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS))


@jax.jit
def _moments_step(carry, X, m):
    """carry: (n, s1, s2, mn, mx); X f32[rows, d] (sharded over data), m
    f32[rows] validity mask (0 for padding rows)."""
    n, s1, s2, mn, mx = carry
    Xm = X * m[:, None]
    n = n + m.sum()
    s1 = s1 + Xm.sum(axis=0)
    s2 = s2 + (X * Xm).sum(axis=0)
    mn = jnp.minimum(mn, jnp.where(m[:, None] > 0, X, jnp.inf).min(axis=0))
    mx = jnp.maximum(mx, jnp.where(m[:, None] > 0, X, -jnp.inf).max(axis=0))
    return n, s1, s2, mn, mx


@jax.jit
def _gram_step(carry, X, yv, m, mean, y_mean):
    """carry: (G [d,d], gy [d], yy, n); accumulates the centered Gram."""
    G, gy, yy, n = carry
    Z = (X - mean[None, :]) * m[:, None]
    zy = (yv - y_mean) * m
    G = G + Z.T @ Z
    gy = gy + Z.T @ zy
    yy = yy + (zy * zy).sum()
    n = n + m.sum()
    return G, gy, yy, n


class DataShardedStats:
    """Two-pass streaming moments + correlations over row chunks.

    ``mesh=None`` runs single-device (same code path; XLA elides the
    collectives) — the Spark local-mode analog.  Chunks may be any row
    count; they are padded to the data-shard multiple with masked rows.
    """

    def __init__(self, d: int, mesh=None):
        self.d = d
        self.mesh = mesh
        self.n_shards = int(mesh.shape[DATA_AXIS]) if mesh is not None else 1

    def _place(self, arr: np.ndarray):
        if self.mesh is None:
            return jnp.asarray(arr)
        return jax.device_put(jnp.asarray(arr), _data_sharding(self.mesh))

    def _chunks_masked(self, chunks: Iterable[np.ndarray]
                       ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        for X in chunks:
            X = np.ascontiguousarray(np.asarray(X, np.float32))
            rows = X.shape[0]
            pad = (-rows) % self.n_shards
            m = np.ones(rows, np.float32)
            if pad:
                X = np.concatenate([X, np.zeros((pad, X.shape[1]), np.float32)])
                m = np.concatenate([m, np.zeros(pad, np.float32)])
            yield X, m

    # ---- pass 1 ------------------------------------------------------------
    def moments(self, chunks: Iterable[np.ndarray]) -> ColStats:
        d = self.d
        carry = (jnp.zeros(()), jnp.zeros(d), jnp.zeros(d),
                 jnp.full(d, jnp.inf), jnp.full(d, -jnp.inf))
        for X, m in self._chunks_masked(chunks):
            carry = _moments_step(carry, self._place(X), self._place(m))
        n, s1, s2, mn, mx = (np.asarray(c, np.float64) for c in carry)
        # cross-host tier: raw sums add, min/max lattice-merge (identity
        # single-process)
        packed = host_sum_reduce(np.concatenate([[float(n)], s1, s2]),
                                 "moments_raw")
        n, s1, s2 = packed[0], packed[1:1 + d], packed[1 + d:]
        mn, mx = host_merge_minmax(mn, mx)
        n = float(n)
        mean = s1 / max(n, 1.0)
        var = np.maximum(s2 / max(n, 1.0) - mean * mean, 0.0) * (
            n / max(n - 1.0, 1.0))  # sample variance (Spark colStats)
        return ColStats(count=int(n), mean=mean, variance=var, min=mn, max=mx)

    # ---- pass 2 ------------------------------------------------------------
    def correlations_from(self, chunks_factory, mean: np.ndarray, y_mean: float,
                          with_corr_matrix: bool = True
                          ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """``chunks_factory()`` yields (X_chunk [rows, d], y_chunk [rows])
        pairs.  Returns (corr_with_label [d], corr_matrix [d,d] | None)."""
        d = self.d
        meand = jnp.asarray(mean, jnp.float32)
        ymd = jnp.asarray(np.float32(y_mean))
        carry = (jnp.zeros((d, d)), jnp.zeros(d), jnp.zeros(()), jnp.zeros(()))
        for X, y in chunks_factory():
            X = np.ascontiguousarray(np.asarray(X, np.float32))
            y = np.asarray(y, np.float32)
            rows = X.shape[0]
            pad = (-rows) % self.n_shards
            m = np.ones(rows, np.float32)
            if pad:
                X = np.concatenate([X, np.zeros((pad, d), np.float32)])
                y = np.concatenate([y, np.zeros(pad, np.float32)])
                m = np.concatenate([m, np.zeros(pad, np.float32)])
            carry = _gram_step(carry, self._place(X), self._place(y),
                               self._place(m), meand, ymd)
        G, gy, yy, n = (np.asarray(c, np.float64) for c in carry)
        # cross-host tier: every host's Gram is centered at the SAME global
        # mean (pass 1 already merged), so the carries are plain sums
        packed = host_sum_reduce(
            np.concatenate([[float(n), float(yy)], gy, G.reshape(-1)]),
            "gram")
        n, yy = packed[0], packed[1]
        gy = packed[2:2 + d]
        G = packed[2 + d:].reshape(d, d)
        diag = np.diag(G).copy()
        zero = diag <= 0.0
        denom = np.sqrt(np.maximum(diag, 1e-300))
        with np.errstate(invalid="ignore", divide="ignore"):
            corr_label = gy / (denom * np.sqrt(max(float(yy), 1e-300)))
        corr_label[zero] = np.nan
        corr_matrix = None
        if with_corr_matrix:
            corr_matrix = G / np.outer(denom, denom)
            np.fill_diagonal(corr_matrix, 1.0)
            corr_matrix[zero, :] = np.nan
            corr_matrix[:, zero] = np.nan
        return corr_label, corr_matrix


def chunked(X: np.ndarray, y: Optional[np.ndarray] = None,
            chunk_rows: int = 1 << 18):
    """Row-chunk an in-memory array (factory usable for both passes)."""
    n = X.shape[0]

    def gen_x():
        for lo in range(0, n, chunk_rows):
            yield X[lo:lo + chunk_rows]

    if y is None:
        return gen_x

    def gen_xy():
        for lo in range(0, n, chunk_rows):
            yield X[lo:lo + chunk_rows], y[lo:lo + chunk_rows]

    return gen_xy


@jax.jit
def _fused_stats_step(carry, X, yv, m):
    """ONE-pass moments + mean-centered Gram via Chan's pairwise merge.

    carry: (n, mean[d], y_mean, mn, mx, G[d,d], gy[d], yy) where G/gy/yy are
    centered at the CARRY means.  Each chunk is centered at its OWN means
    and merged with the exact pairwise-update cross terms
    (f = n0*nc/(n0+nc); G += Gc + f dx dx^T; gy += gyc + f dx dy;
    yy += yyc + f dy^2), so no large-offset cancellation ever enters the
    f32 accumulators — a constant-center scheme would cancel catastrophically
    on row-ordered data whose mean drifts.  ONE pass means each chunk
    uploads once: on a tunneled backend the second upload of the matrix was
    the single largest cost of the two-pass scheme (round-5 measurement:
    ~63 MB/s real upload bandwidth on incompressible data).
    """
    n0, mean0, ym0, mn, mx, G, gy, yy = carry
    nc = m.sum()
    ncs = jnp.maximum(nc, 1.0)
    mc = (X * m[:, None]).sum(axis=0) / ncs
    yc = (yv * m).sum() / ncs
    Z = (X - mc[None, :]) * m[:, None]
    zy = (yv - yc) * m
    Gc = Z.T @ Z
    gyc = Z.T @ zy
    yyc = (zy * zy).sum()
    nt = n0 + nc
    f = jnp.where(nt > 0, n0 * nc / jnp.maximum(nt, 1.0), 0.0)
    dx = mc - mean0
    dy = yc - ym0
    G = G + Gc + f * jnp.outer(dx, dx)
    gy = gy + gyc + f * dx * dy
    yy = yy + yyc + f * dy * dy
    w = nc / jnp.maximum(nt, 1.0)
    mean = mean0 + dx * w
    ym = ym0 + dy * w
    mn = jnp.minimum(mn, jnp.where(m[:, None] > 0, X, jnp.inf).min(axis=0))
    mx = jnp.maximum(mx, jnp.where(m[:, None] > 0, X, -jnp.inf).max(axis=0))
    return nt, mean, ym, mn, mx, G, gy, yy


@jax.jit
def _chan_moments_step(carry, X, m):
    """One Chan pairwise-merge step of streaming column moments.

    carry: (n, mean[d], M2[d]) with M2 the CENTERED sum of squares.  The
    chunk is centered at its OWN mean and merged with the exact pairwise
    cross term (the _fused_stats_step recipe minus the Gram), so no raw
    second moments enter the f32 accumulator.  m masks padding rows."""
    n0, mean0, M2 = carry
    nc = m.sum()
    ncs = jnp.maximum(nc, 1.0)
    mc = (X * m[:, None]).sum(axis=0) / ncs
    Z = (X - mc[None, :]) * m[:, None]
    M2c = (Z * Z).sum(axis=0)
    nt = n0 + nc
    f = jnp.where(nt > 0, n0 * nc / jnp.maximum(nt, 1.0), 0.0)
    dx = mc - mean0
    M2 = M2 + M2c + f * dx * dx
    mean = mean0 + dx * (nc / jnp.maximum(nt, 1.0))
    return nt, mean, M2


def _merge_moment_carries(carries):
    """Chan-merge per-device (n, mean, M2) partials host-side in f64 — the
    cross-device half of the reduction (ROADMAP item 4's per-host merge
    pattern, applied across the stream devices of one host)."""
    n_t: float = 0.0
    mean_t = M2_t = None
    for c in carries:
        n_c, mean_c, M2_c = (np.asarray(x, np.float64) for x in c)
        n_c = float(n_c)
        if n_c <= 0:
            continue
        if mean_t is None:
            n_t, mean_t, M2_t = n_c, mean_c, M2_c
            continue
        nt = n_t + n_c
        dx = mean_c - mean_t
        M2_t = M2_t + M2_c + (n_t * n_c / nt) * dx * dx
        mean_t = mean_t + dx * (n_c / nt)
        n_t = nt
    return n_t, mean_t, M2_t


# ---------------------------------------------------------------------------
# Host-level merge tier — the cross-host (DCN) half of the fit statistics.
#
# Per-device Chan partials merge on each host (``_merge_moment_carries``);
# under ``jax.distributed`` the per-host results then cross the host boundary
# ONCE as a tiny f64 carry (O(d) floats, never row data) via
# ``process_allgather``, and every host merges the SAME ordered list in f64 —
# deterministic and bit-identical across hosts.  Single-process runs skip all
# of it (``jax.process_count() == 1`` → the carry passes through untouched),
# so the one-host path stays byte-identical.
# ---------------------------------------------------------------------------


#: per-kind monotone sequence for the coordination-service transport: every
#: host performs the SAME gathers in the SAME order (an all-gather invariant
#: already), so the counter names each round's keys identically everywhere
_KV_SEQ: dict = {}


def _kv_gather(raw: np.ndarray, kind: str):
    """All-gather raw bytes through the jax.distributed coordination-service
    key-value store (pure gRPC — no XLA computation involved).

    This is the CPU-proxy transport: XLA:CPU refuses multiprocess
    computations outright ("Multiprocess computations aren't implemented on
    the CPU backend"), so the two-process CI topology exchanges its moment
    carries host->coordinator->host instead.  Payloads are per-host moment
    carries (KBs), not row data — the store is never a data plane."""
    from jax._src import distributed

    client = distributed.global_state.client
    seq = _KV_SEQ.get(kind, 0)
    _KV_SEQ[kind] = seq + 1
    me = int(jax.process_index())
    client.key_value_set_bytes(f"tmog_gather/{kind}/{seq}/{me}",
                               raw.tobytes())
    out = []
    for h in range(int(jax.process_count())):
        buf = client.blocking_key_value_get_bytes(
            f"tmog_gather/{kind}/{seq}/{h}", 120_000)
        out.append(np.frombuffer(bytes(buf), np.uint8))
    return out


def _cross_host_gather(vec64: np.ndarray, kind: str):
    """All-gather one f64 vector across processes -> list of per-host rows.

    The payload crosses DCN as raw bytes (uint8 view), so the f64 carries
    survive even with jax x64 disabled.  Each gather is counted in the
    ``host`` obs scope (kind, payload bytes) — the cross-host analog of the
    ``mesh_psum`` trace telemetry."""
    from ..obs.registry import scope as _scope

    raw = np.ascontiguousarray(np.asarray(vec64, np.float64)).view(np.uint8)
    sc = _scope("host")
    sc.inc("collectives")
    sc.inc("collective_bytes", float(raw.nbytes))
    sc.append("events", {"kind": kind, "bytes": int(raw.nbytes)})
    if jax.default_backend() == "cpu":
        rows8 = _kv_gather(raw, kind)
    else:
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(raw))
        rows8 = [np.ascontiguousarray(gathered[i])
                 for i in range(gathered.shape[0])]
    return [row.view(np.float64) for row in rows8]


def _multi_host() -> bool:
    try:
        return int(jax.process_count()) > 1
    except Exception:
        return False


def host_merge_moments(carry, d: int):
    """Merge one host's (n, mean[d], M2[d]) Chan carry into the GLOBAL carry.

    A host with an empty row range contributes an exact zero carry (its
    ``mean`` may be None).  Single-process: identity."""
    n, mean, M2 = carry
    if not _multi_host():
        return carry
    if mean is None:
        n, mean, M2 = 0.0, np.zeros(d), np.zeros(d)
    packed = np.concatenate([[float(n)], np.asarray(mean, np.float64),
                             np.asarray(M2, np.float64)])
    rows = _cross_host_gather(packed, "moments")
    return _merge_moment_carries(
        [(r[0], r[1:1 + d], r[1 + d:]) for r in rows])


def host_sum_reduce(parts, kind: str = "sum"):
    """Element-wise sum of a flat f64 vector across hosts (for carries
    already centered at a GLOBAL reference — raw sums, common-mean Grams).
    min/max components must not ride through this; see
    ``host_merge_minmax``.  Single-process: identity."""
    parts = np.asarray(parts, np.float64)
    if not _multi_host():
        return parts
    rows = _cross_host_gather(parts, kind)
    return np.sum(np.stack(rows, axis=0), axis=0)


def host_merge_minmax(mn, mx):
    """Global element-wise column min/max across hosts (empty-range hosts
    hold ±inf identities).  Single-process: identity."""
    mn = np.asarray(mn, np.float64)
    mx = np.asarray(mx, np.float64)
    if not _multi_host():
        return mn, mx
    d = mn.shape[0]
    rows = _cross_host_gather(np.concatenate([mn, mx]), "minmax")
    stacked = np.stack(rows, axis=0)
    return stacked[:, :d].min(axis=0), stacked[:, d:].max(axis=0)


def host_merge_fused_carry(carry, d: int):
    """Chan-merge the fused one-pass carry (n, mean, ym, mn, mx, G, gy, yy)
    across hosts in f64 — exact pairwise cross terms for the Gram, so the
    global correlations match a single-host pass to f32-accumulation noise.
    Single-process: identity."""
    if not _multi_host():
        return carry
    n, mean, ym, mn, mx, G, gy, yy = (np.asarray(c, np.float64)
                                      for c in carry)
    packed = np.concatenate([[float(n), float(ym), float(yy)], mean, mn, mx,
                             gy, G.reshape(-1)])
    rows = _cross_host_gather(packed, "fused_stats")
    nt = 0.0
    mean_t = ym_t = G_t = gy_t = yy_t = None
    mn_t = np.full(d, np.inf)
    mx_t = np.full(d, -np.inf)
    for r in rows:
        n_c, ym_c, yy_c = r[0], r[1], r[2]
        o = 3
        mean_c = r[o:o + d]; o += d
        mn_c = r[o:o + d]; o += d
        mx_c = r[o:o + d]; o += d
        gy_c = r[o:o + d]; o += d
        G_c = r[o:].reshape(d, d)
        mn_t = np.minimum(mn_t, mn_c)
        mx_t = np.maximum(mx_t, mx_c)
        if n_c <= 0:
            continue
        if mean_t is None:
            nt, mean_t, ym_t = n_c, mean_c, ym_c
            G_t, gy_t, yy_t = G_c, gy_c, yy_c
            continue
        ns = nt + n_c
        f = nt * n_c / ns
        dx = mean_c - mean_t
        dy = ym_c - ym_t
        G_t = G_t + G_c + f * np.outer(dx, dx)
        gy_t = gy_t + gy_c + f * dx * dy
        yy_t = yy_t + yy_c + f * dy * dy
        w = n_c / ns
        mean_t = mean_t + dx * w
        ym_t = ym_t + dy * w
        nt = ns
    if mean_t is None:
        z = np.zeros(d)
        return 0.0, z, 0.0, mn_t, mx_t, np.zeros((d, d)), z.copy(), 0.0
    return nt, mean_t, ym_t, mn_t, mx_t, G_t, gy_t, yy_t


def sharded_column_moments(X: np.ndarray, chunk_rows: int = 1 << 18,
                           devices: Optional[list] = None
                           ) -> Tuple[float, np.ndarray, np.ndarray]:
    """Column mean and POPULATION std of ``X [n, d]`` via per-device
    round-robin Chan partials.

    Chunk i accumulates into device i-mod-D's carry, so each device runs an
    independent async accumulation pipeline (no per-chunk lockstep
    collective, unlike the mesh-placed ``DataShardedStats``), and the D
    partial carries merge exactly at the end.  This is what the streamed
    scaler fit reduces through when the transform stream is sharded — fit
    and transform ride the same devices.  Returns ``(count, mean, std)``
    f64; ``devices=None``/single runs the identical math on the default
    device."""
    X = np.asarray(X)
    n = X.shape[0]
    d = X.shape[1] if X.ndim > 1 else 1
    X = X.reshape(n, d)
    devices = list(devices) if devices else [None]
    D = len(devices)
    carries: list = [None] * D
    for k, lo in enumerate(range(0, n, chunk_rows)):
        chunk = np.ascontiguousarray(X[lo:lo + chunk_rows], np.float32)
        rows = chunk.shape[0]
        m = np.ones(rows, np.float32)
        if rows < chunk_rows:  # constant chunk shape: one compile per device
            chunk = np.concatenate(
                [chunk, np.zeros((chunk_rows - rows, d), np.float32)])
            m = np.concatenate([m, np.zeros(chunk_rows - rows, np.float32)])
        di = k % D
        dev = devices[di]
        if carries[di] is None:
            z = (jnp.zeros(()), jnp.zeros(d), jnp.zeros(d))
            carries[di] = jax.device_put(z, dev) if dev is not None else z
        xa = jax.device_put(chunk, dev) if dev is not None \
            else jnp.asarray(chunk)
        ma = jax.device_put(m, dev) if dev is not None else jnp.asarray(m)
        carries[di] = _chan_moments_step(carries[di], xa, ma)
    n_t, mean, M2 = host_merge_moments(_merge_moment_carries(
        [c for c in carries if c is not None]), d)
    if not n_t or mean is None:
        z = np.zeros(d)
        return 0.0, z, z.copy()
    return n_t, mean, np.sqrt(np.maximum(M2, 0.0) / n_t)


@jax.jit
def _midrank_cols(Xb):
    """Per-column average-tie midranks (1-based): f32[n, k] -> f32[n, k]."""

    def one(col):
        order = jnp.argsort(col)
        ss = col[order]
        lo = jnp.searchsorted(ss, ss, side="left")
        hi = jnp.searchsorted(ss, ss, side="right")
        mid = (lo + hi + 1).astype(jnp.float32) * 0.5
        return jnp.zeros_like(mid).at[order].set(mid)

    return jax.vmap(one, in_axes=1, out_axes=1)(Xb)


def rank_transform(X: np.ndarray, block_cols: int = 128) -> np.ndarray:
    """Global average-tie ranks per column, computed on device in column
    blocks (the Spearman prep; parity with utils/stats._rank_data)."""
    X = np.asarray(X, np.float32)
    if X.ndim == 1:
        return rank_transform(X[:, None], block_cols)[:, 0]
    n, d = X.shape
    out = np.empty((n, d), np.float32)
    for lo in range(0, d, block_cols):
        blk = np.ascontiguousarray(X[:, lo:lo + block_cols])
        out[:, lo:lo + block_cols] = np.asarray(_midrank_cols(jnp.asarray(blk)))
    return out


def fused_moments_and_correlations(chunks_factory, d: int, mesh=None,
                                   with_corr_matrix: bool = True
                                   ) -> Tuple[ColStats, np.ndarray,
                                              Optional[np.ndarray]]:
    """ONE streaming pass: column moments AND label/feature correlations.

    ``chunks_factory()`` yields (X_chunk [rows, d], y_chunk [rows]) pairs —
    each chunk uploads ONCE (the two-pass scheme re-uploaded the whole
    matrix for the Gram pass; uploads dominate on a tunneled link).  Gram,
    mean, and variance accumulate with Chan's numerically-stable pairwise
    merge (see _fused_stats_step); variance falls out of the centered
    Gram's diagonal.
    """
    acc = DataShardedStats(d, mesh=mesh)
    carry = None
    for X, y in chunks_factory():
        X = np.ascontiguousarray(np.asarray(X, np.float32))
        y = np.asarray(y, np.float32)
        rows = X.shape[0]
        pad = (-rows) % acc.n_shards
        m = np.ones(rows, np.float32)
        if pad:
            X = np.concatenate([X, np.zeros((pad, d), np.float32)])
            y = np.concatenate([y, np.zeros(pad, np.float32)])
            m = np.concatenate([m, np.zeros(pad, np.float32)])
        if carry is None:
            carry = (jnp.zeros(()), jnp.zeros(d), jnp.zeros(()),
                     jnp.full(d, jnp.inf), jnp.full(d, -jnp.inf),
                     jnp.zeros((d, d)), jnp.zeros(d), jnp.zeros(()))
        carry = _fused_stats_step(carry, acc._place(X), acc._place(y),
                                  acc._place(m))
    if carry is None:
        if _multi_host():
            # an empty-range host still joins the cross-host merge with an
            # exact zero carry — the other hosts' allgather must not hang
            carry = (jnp.zeros(()), jnp.zeros(d), jnp.zeros(()),
                     jnp.full(d, jnp.inf), jnp.full(d, -jnp.inf),
                     jnp.zeros((d, d)), jnp.zeros(d), jnp.zeros(()))
        else:
            z = np.zeros(d)
            return ColStats(0, z, z.copy(), z.copy(), z.copy()), \
                np.full(d, np.nan), None
    carry = host_merge_fused_carry(carry, d)
    n_, mean, _ym, mn, mx, G, gy, yy = (np.asarray(c, np.float64)
                                        for c in carry)
    n = float(n_)
    yy = float(yy)
    # sample variance straight off the centered Gram's diagonal
    var = np.maximum(np.diag(G), 0.0) / max(n - 1.0, 1.0)
    stats = ColStats(count=int(n), mean=mean, variance=var, min=mn, max=mx)
    diag = np.diag(G).copy()
    zero = diag <= 0.0
    denom = np.sqrt(np.maximum(diag, 1e-300))
    with np.errstate(invalid="ignore", divide="ignore"):
        corr_label = gy / (denom * np.sqrt(max(yy, 1e-300)))
    corr_label[zero] = np.nan
    corr_matrix = None
    if with_corr_matrix:
        corr_matrix = G / np.outer(denom, denom)
        np.fill_diagonal(corr_matrix, 1.0)
        corr_matrix[zero, :] = np.nan
        corr_matrix[:, zero] = np.nan
    return stats, corr_label, corr_matrix


def sharded_correlations(X: np.ndarray, y: np.ndarray, mesh=None,
                         with_corr_matrix: bool = True,
                         chunk_rows: int = 1 << 18, method: str = "pearson"
                         ) -> Tuple[ColStats, np.ndarray, Optional[np.ndarray]]:
    """Drop-in large-data correlation path for SanityChecker: two sharded
    streaming passes over row chunks.  ``method`` "spearman" rank-transforms
    every column on device first (one extra [n, d] f32 materialization) and
    streams Pearson over the ranks; column stats are always raw-space.
    Returns (col_stats, corr_with_label, corr_matrix|None) matching
    utils/stats.correlations_with_label."""
    n = X.shape[0]
    acc = DataShardedStats(X.shape[1], mesh=mesh)
    stats = acc.moments(chunked(X, chunk_rows=chunk_rows)())
    if method == "spearman":
        Xc = rank_transform(X)
        yc = rank_transform(np.asarray(y, np.float32))
        mean_c = np.full(X.shape[1], (n + 1) / 2.0)  # midrank mean, exact
        y_mean = (n + 1) / 2.0
    else:
        Xc, yc = X, y
        mean_c = stats.mean
        y64 = np.asarray(y, np.float64)
        y_mean = float(y64.mean()) if len(y64) else 0.0
    corr_label, corr_matrix = acc.correlations_from(
        chunked(Xc, yc, chunk_rows=chunk_rows), mean_c, y_mean,
        with_corr_matrix=with_corr_matrix)
    return stats, corr_label, corr_matrix
