"""Evaluators (reference core/src/main/scala/com/salesforce/op/evaluators/).

``Evaluators`` factory mirrors ``Evaluators.BinaryClassification.auPR()`` etc.
(Evaluators.scala:40), including custom-metric evaluators.
"""
from typing import Callable, Optional

import numpy as np

from .base import (OpBinaryClassificationEvaluatorBase, OpEvaluatorBase,
                   OpMultiClassificationEvaluatorBase, OpRegressionEvaluatorBase)
from .classification import (OpBinaryClassificationEvaluator, OpBinScoreEvaluator,
                             OpLogLoss, OpMultiClassificationEvaluator,
                             binary_counts, pr_auc, roc_auc)
from .regression import OpForecastEvaluator, OpRegressionEvaluator


class _SingleMetric(OpEvaluatorBase):
    """Wrap a full evaluator, exposing one metric as the default."""

    def __init__(self, inner: OpEvaluatorBase, metric: str, larger_better: bool):
        super().__init__(inner.label_col, inner.prediction_col)
        self.inner = inner
        self.name = f"{inner.name}.{metric}"
        self.default_metric = metric
        self.is_larger_better = larger_better

    def evaluate_all(self, ds, label_col=None, prediction_col=None):
        return self.inner.evaluate_all(ds, label_col, prediction_col)

    def evaluate_arrays(self, y, prediction, probability=None):
        return self.inner.evaluate_arrays(y, prediction, probability)


class CustomEvaluator(OpEvaluatorBase):
    """User-defined metric (Evaluators.BinaryClassification.custom analog)."""

    def __init__(self, metric_name: str, is_larger_better: bool,
                 fn: Callable[[np.ndarray, np.ndarray, Optional[np.ndarray]], float],
                 label_col: Optional[str] = None, prediction_col: Optional[str] = None):
        super().__init__(label_col, prediction_col)
        self.name = f"custom.{metric_name}"
        self.default_metric = metric_name
        self.is_larger_better = is_larger_better
        self.fn = fn

    def evaluate_arrays(self, y, prediction, probability=None):
        return {self.default_metric: float(self.fn(y, prediction, probability))}

    def evaluate_all(self, ds, label_col=None, prediction_col=None):
        y, pred = self._extract(ds, label_col, prediction_col)
        return self.evaluate_arrays(y, pred.prediction, pred.probability)


class Evaluators:
    class BinaryClassification:
        @staticmethod
        def auROC() -> OpEvaluatorBase:
            return _SingleMetric(OpBinaryClassificationEvaluator(), "AuROC", True)

        @staticmethod
        def auPR() -> OpEvaluatorBase:
            return _SingleMetric(OpBinaryClassificationEvaluator(), "AuPR", True)

        @staticmethod
        def precision() -> OpEvaluatorBase:
            return _SingleMetric(OpBinaryClassificationEvaluator(), "Precision", True)

        @staticmethod
        def recall() -> OpEvaluatorBase:
            return _SingleMetric(OpBinaryClassificationEvaluator(), "Recall", True)

        @staticmethod
        def f1() -> OpEvaluatorBase:
            return _SingleMetric(OpBinaryClassificationEvaluator(), "F1", True)

        @staticmethod
        def error() -> OpEvaluatorBase:
            return _SingleMetric(OpBinaryClassificationEvaluator(), "Error", False)

        @staticmethod
        def brierScore() -> OpEvaluatorBase:
            return OpBinScoreEvaluator()

        @staticmethod
        def custom(metric_name: str, is_larger_better: bool, fn) -> OpEvaluatorBase:
            return CustomEvaluator(metric_name, is_larger_better, fn)

    class MultiClassification:
        @staticmethod
        def f1() -> OpEvaluatorBase:
            return _SingleMetric(OpMultiClassificationEvaluator(), "F1", True)

        @staticmethod
        def precision() -> OpEvaluatorBase:
            return _SingleMetric(OpMultiClassificationEvaluator(), "Precision", True)

        @staticmethod
        def recall() -> OpEvaluatorBase:
            return _SingleMetric(OpMultiClassificationEvaluator(), "Recall", True)

        @staticmethod
        def error() -> OpEvaluatorBase:
            return _SingleMetric(OpMultiClassificationEvaluator(), "Error", False)

        @staticmethod
        def logLoss() -> OpEvaluatorBase:
            return OpLogLoss()

        @staticmethod
        def custom(metric_name: str, is_larger_better: bool, fn) -> OpEvaluatorBase:
            return CustomEvaluator(metric_name, is_larger_better, fn)

    class Regression:
        @staticmethod
        def rmse() -> OpEvaluatorBase:
            return _SingleMetric(OpRegressionEvaluator(), "RootMeanSquaredError", False)

        @staticmethod
        def mse() -> OpEvaluatorBase:
            return _SingleMetric(OpRegressionEvaluator(), "MeanSquaredError", False)

        @staticmethod
        def mae() -> OpEvaluatorBase:
            return _SingleMetric(OpRegressionEvaluator(), "MeanAbsoluteError", False)

        @staticmethod
        def r2() -> OpEvaluatorBase:
            return _SingleMetric(OpRegressionEvaluator(), "R2", True)

        @staticmethod
        def smape() -> OpEvaluatorBase:
            return OpForecastEvaluator()

        @staticmethod
        def custom(metric_name: str, is_larger_better: bool, fn) -> OpEvaluatorBase:
            return CustomEvaluator(metric_name, is_larger_better, fn)


__all__ = [n for n in dir() if not n.startswith("_")]
