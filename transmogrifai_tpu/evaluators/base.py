"""Evaluator base classes.

Reference parity: core/src/main/scala/com/salesforce/op/evaluators/
``OpEvaluatorBase`` (:113): name, ``isLargerBetter``, ``evaluate`` (default
metric) / ``evaluateAll`` (full metric map).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..columns import Dataset, NumericColumn, PredictionColumn


class OpEvaluatorBase:
    name: str = "evaluator"
    default_metric: str = ""
    is_larger_better: bool = True

    def __init__(self, label_col: Optional[str] = None, prediction_col: Optional[str] = None):
        self.label_col = label_col
        self.prediction_col = prediction_col

    # ---- column extraction -------------------------------------------------
    def _extract(self, ds: Dataset, label_col: Optional[str], prediction_col: Optional[str]
                 ) -> Tuple[np.ndarray, PredictionColumn]:
        label_col = label_col or self.label_col
        prediction_col = prediction_col or self.prediction_col
        if label_col is None or prediction_col is None:
            raise ValueError(f"{self.name}: label/prediction columns not set")
        lab = ds[label_col]
        assert isinstance(lab, NumericColumn), f"label column {label_col} must be numeric"
        pred = ds[prediction_col]
        assert isinstance(pred, PredictionColumn), \
            f"prediction column {prediction_col} must be a Prediction"
        if not lab.mask.all():  # unlabeled rows never contribute to metrics
            keep = np.where(lab.mask)[0]
            lab = lab.take(keep)
            pred = pred.take(keep)
        return lab.values.astype(np.float64), pred

    def evaluate_all(self, ds: Dataset, label_col: Optional[str] = None,
                     prediction_col: Optional[str] = None) -> Dict[str, Any]:
        raise NotImplementedError

    def evaluate(self, ds: Dataset, label_col: Optional[str] = None,
                 prediction_col: Optional[str] = None) -> float:
        return float(self.evaluate_all(ds, label_col, prediction_col)[self.default_metric])

    def evaluate_arrays(self, y: np.ndarray, prediction: np.ndarray,
                        probability: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """Array fast path used by the model-selector sweep (no Dataset)."""
        raise NotImplementedError


class OpBinaryClassificationEvaluatorBase(OpEvaluatorBase):
    pass


class OpMultiClassificationEvaluatorBase(OpEvaluatorBase):
    pass


class OpRegressionEvaluatorBase(OpEvaluatorBase):
    pass
