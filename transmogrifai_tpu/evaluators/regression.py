"""Regression + forecast evaluators.

Reference parity:
- ``OpRegressionEvaluator`` (evaluators/OpRegressionEvaluator.scala:55):
  RMSE (default), MSE, R², MAE + signed-percentage-error histogram,
- ``OpForecastEvaluator`` (:59): SMAPE, (seasonal) MASE.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .base import OpRegressionEvaluatorBase


class OpRegressionEvaluator(OpRegressionEvaluatorBase):
    name = "regEval"
    default_metric = "RootMeanSquaredError"
    is_larger_better = False

    def __init__(self, label_col: Optional[str] = None, prediction_col: Optional[str] = None,
                 percentage_error_histogram_bins: Optional[List[float]] = None):
        super().__init__(label_col, prediction_col)
        self.hist_bins = percentage_error_histogram_bins or \
            [float("-inf"), -100.0, -50.0, -25.0, -10.0, 0.0, 10.0, 25.0, 50.0, 100.0,
             float("inf")]

    def evaluate_arrays(self, y, prediction, probability=None) -> Dict[str, Any]:
        y = np.asarray(y, dtype=np.float64)
        pred = np.asarray(prediction, dtype=np.float64)
        n = max(len(y), 1)
        err = pred - y
        mse = float(np.mean(err ** 2)) if len(y) else 0.0
        ss_tot = float(((y - y.mean()) ** 2).sum()) if len(y) else 0.0
        r2 = 1.0 - float((err ** 2).sum()) / ss_tot if ss_tot > 0 else 0.0
        # signed percentage errors (SignedPercentageErrorHistogram)
        with np.errstate(divide="ignore", invalid="ignore"):
            pct = np.where(y != 0, 100.0 * err / np.abs(y), np.sign(err) * np.inf)
        counts, _ = np.histogram(pct[np.isfinite(pct)], bins=self.hist_bins)
        return {
            "RootMeanSquaredError": float(np.sqrt(mse)),
            "MeanSquaredError": mse,
            "R2": r2,
            "MeanAbsoluteError": float(np.mean(np.abs(err))) if len(y) else 0.0,
            "SignedPercentageErrorHistogram": {
                "bins": [b for b in self.hist_bins],
                "counts": counts.tolist(),
            },
        }

    def evaluate_all(self, ds, label_col=None, prediction_col=None) -> Dict[str, Any]:
        y, pred = self._extract(ds, label_col, prediction_col)
        return self.evaluate_arrays(y, pred.prediction)


class OpForecastEvaluator(OpRegressionEvaluatorBase):
    """Forecast metrics (OpForecastEvaluator.scala:59): SMAPE + seasonal MASE."""

    name = "forecastEval"
    default_metric = "SMAPE"
    is_larger_better = False

    def __init__(self, label_col: Optional[str] = None, prediction_col: Optional[str] = None,
                 seasonal_window: int = 1):
        super().__init__(label_col, prediction_col)
        self.seasonal_window = seasonal_window

    def evaluate_arrays(self, y, prediction, probability=None) -> Dict[str, Any]:
        y = np.asarray(y, dtype=np.float64)
        pred = np.asarray(prediction, dtype=np.float64)
        denom = np.abs(y) + np.abs(pred)
        smape = float(2.0 * np.mean(np.where(denom > 0, np.abs(pred - y) / denom, 0.0))) \
            if len(y) else 0.0
        m = self.seasonal_window
        if len(y) > m:
            naive = np.mean(np.abs(y[m:] - y[:-m]))
            mase = float(np.mean(np.abs(pred - y)) / naive) if naive > 0 else 0.0
        else:
            mase = 0.0
        return {"SMAPE": smape, "SeasonalError": mase, "MASE": mase}

    def evaluate_all(self, ds, label_col=None, prediction_col=None) -> Dict[str, Any]:
        y, pred = self._extract(ds, label_col, prediction_col)
        return self.evaluate_arrays(y, pred.prediction)
