"""Classification evaluators.

Reference parity:
- ``OpBinaryClassificationEvaluator`` (evaluators/OpBinaryClassificationEvaluator.scala:56):
  AuROC (default), AuPR, Precision, Recall, F1, Error, TP/TN/FP/FN + threshold
  curves,
- ``OpMultiClassificationEvaluator`` (:59): Error, Precision, Recall, F1
  (weighted) + top-K thresholded metrics + confidence histograms,
- ``OpBinScoreEvaluator`` (OpBinScoreEvaluator.scala:53): calibration bins
  (BrierScore, bin centers/counts/avg scores/conversion rates),
- ``OPLogLoss`` (impl/evaluator/OPLogLoss.scala).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .base import (OpBinaryClassificationEvaluatorBase, OpEvaluatorBase,
                   OpMultiClassificationEvaluatorBase)


def roc_auc(y: np.ndarray, score: np.ndarray) -> float:
    """AuROC via rank statistic (equivalent to trapezoid over the full curve)."""
    pos = score[y == 1]
    neg = score[y == 0]
    if len(pos) == 0 or len(neg) == 0:
        return 0.0
    order = np.argsort(np.concatenate([pos, neg]), kind="mergesort")
    ranks = np.empty(len(order), dtype=np.float64)
    ranks[order] = np.arange(1, len(order) + 1)
    # midrank correction for ties
    allv = np.concatenate([pos, neg])
    sorted_v = allv[order]
    i = 0
    sr = ranks[order]
    while i < len(sorted_v):
        j = i
        while j + 1 < len(sorted_v) and sorted_v[j + 1] == sorted_v[i]:
            j += 1
        if j > i:
            sr[i:j + 1] = (i + j) / 2.0 + 1.0
        i = j + 1
    ranks[order] = sr
    r_pos = ranks[: len(pos)].sum()
    n_pos, n_neg = len(pos), len(neg)
    return float((r_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def pr_auc(y: np.ndarray, score: np.ndarray) -> float:
    """Area under precision-recall (step-wise, Spark BinaryClassificationMetrics
    style: first point (0, p0) then one point per distinct threshold)."""
    n_pos = int((y == 1).sum())
    if n_pos == 0:
        return 0.0
    order = np.argsort(-score, kind="mergesort")
    ys = y[order]
    ss = score[order]
    tp = np.cumsum(ys)
    fp = np.cumsum(1 - ys)
    # keep last index of each distinct score (threshold boundaries)
    distinct = np.append(ss[1:] != ss[:-1], True)
    tp_d, fp_d = tp[distinct], fp[distinct]
    precision = tp_d / np.maximum(tp_d + fp_d, 1)
    recall = tp_d / n_pos
    prev_r = 0.0
    area = 0.0
    for p, r in zip(precision, recall):
        area += p * (r - prev_r)
        prev_r = r
    return float(area)


def binary_counts(y: np.ndarray, pred: np.ndarray) -> Dict[str, float]:
    tp = float(((y == 1) & (pred == 1)).sum())
    tn = float(((y == 0) & (pred == 0)).sum())
    fp = float(((y == 0) & (pred == 1)).sum())
    fn = float(((y == 1) & (pred == 0)).sum())
    return {"TP": tp, "TN": tn, "FP": fp, "FN": fn}


class OpBinaryClassificationEvaluator(OpBinaryClassificationEvaluatorBase):
    name = "binEval"
    default_metric = "AuROC"
    is_larger_better = True

    def __init__(self, label_col: Optional[str] = None, prediction_col: Optional[str] = None,
                 num_thresholds: int = 100):
        super().__init__(label_col, prediction_col)
        self.num_thresholds = num_thresholds

    def evaluate_arrays(self, y, prediction, probability=None) -> Dict[str, Any]:
        y = np.asarray(y, dtype=np.float64)
        pred = np.asarray(prediction, dtype=np.float64)
        score = np.asarray(probability[:, 1] if probability is not None and probability.ndim == 2
                           else (probability if probability is not None else pred),
                           dtype=np.float64)
        c = binary_counts(y, pred)
        tp, tn, fp, fn = c["TP"], c["TN"], c["FP"], c["FN"]
        n = max(len(y), 1)
        precision = tp / (tp + fp) if tp + fp > 0 else 0.0
        recall = tp / (tp + fn) if tp + fn > 0 else 0.0
        f1 = 2 * precision * recall / (precision + recall) if precision + recall > 0 else 0.0
        out: Dict[str, Any] = {
            "AuROC": roc_auc(y, score),
            "AuPR": pr_auc(y, score),
            "Precision": precision,
            "Recall": recall,
            "F1": f1,
            "Error": (fp + fn) / n,
            **c,
        }
        # threshold curves (thresholds / precisionByThreshold / recallByThreshold
        # / falsePositiveRateByThreshold — OpBinaryClassificationEvaluator)
        thresholds = np.linspace(0.0, 1.0, self.num_thresholds + 1)
        p_list, r_list, fpr_list = [], [], []
        n_pos = max((y == 1).sum(), 1)
        n_neg = max((y == 0).sum(), 1)
        for t in thresholds:
            ph = (score >= t).astype(np.float64)
            tp_t = float(((y == 1) & (ph == 1)).sum())
            fp_t = float(((y == 0) & (ph == 1)).sum())
            p_list.append(tp_t / (tp_t + fp_t) if tp_t + fp_t > 0 else 1.0)
            r_list.append(tp_t / n_pos)
            fpr_list.append(fp_t / n_neg)
        out["thresholds"] = thresholds.tolist()
        out["precisionByThreshold"] = p_list
        out["recallByThreshold"] = r_list
        out["falsePositiveRateByThreshold"] = fpr_list
        return out

    def evaluate_all(self, ds, label_col=None, prediction_col=None) -> Dict[str, Any]:
        y, pred = self._extract(ds, label_col, prediction_col)
        return self.evaluate_arrays(y, pred.prediction, pred.probability)


class OpMultiClassificationEvaluator(OpMultiClassificationEvaluatorBase):
    """Multiclass metrics incl. top-K thresholded metrics
    (OpMultiClassificationEvaluator.scala:59)."""

    name = "multiEval"
    default_metric = "F1"
    is_larger_better = True

    def __init__(self, label_col: Optional[str] = None, prediction_col: Optional[str] = None,
                 top_ns: List[int] = (1, 3), thresholds: Optional[np.ndarray] = None):
        super().__init__(label_col, prediction_col)
        self.top_ns = list(top_ns)
        self.thresholds = np.linspace(0.0, 1.0, 11) if thresholds is None else thresholds

    def evaluate_arrays(self, y, prediction, probability=None) -> Dict[str, Any]:
        y = np.asarray(y, dtype=np.int64)
        pred = np.asarray(prediction, dtype=np.int64)
        n = max(len(y), 1)
        classes = np.unique(np.concatenate([y, pred]))
        # weighted precision/recall/f1 (Spark MulticlassMetrics semantics)
        precisions, recalls, f1s, weights = [], [], [], []
        for c in classes:
            tp = float(((y == c) & (pred == c)).sum())
            fp = float(((y != c) & (pred == c)).sum())
            fn = float(((y == c) & (pred != c)).sum())
            p = tp / (tp + fp) if tp + fp > 0 else 0.0
            r = tp / (tp + fn) if tp + fn > 0 else 0.0
            f = 2 * p * r / (p + r) if p + r > 0 else 0.0
            w = float((y == c).sum()) / n
            precisions.append(p); recalls.append(r); f1s.append(f); weights.append(w)
        out: Dict[str, Any] = {
            "Precision": float(np.dot(precisions, weights)),
            "Recall": float(np.dot(recalls, weights)),
            "F1": float(np.dot(f1s, weights)),
            "Error": float((y != pred).sum()) / n,
        }
        if probability is not None and probability.ndim == 2:
            conf = probability.max(axis=1)
            order = np.argsort(-probability, axis=1)
            found = order == y[:, None]
            # labels outside the model's class range never rank (rank = n_classes)
            correct_rank = np.where(found.any(axis=1), np.argmax(found, axis=1),
                                    probability.shape[1])
            correct_counts: Dict[str, Any] = {}
            incorrect_counts: Dict[str, Any] = {}
            no_pred_counts = []
            for t in self.thresholds:
                no_pred_counts.append(int((conf < t).sum()))
            for k in self.top_ns:
                cc, ic = [], []
                for t in self.thresholds:
                    m = conf >= t
                    correct = int(((correct_rank < k) & m).sum())
                    cc.append(correct)
                    ic.append(int(m.sum()) - correct)
                correct_counts[str(k)] = cc
                incorrect_counts[str(k)] = ic
            out["ThresholdMetrics"] = {
                "topNs": self.top_ns,
                "thresholds": self.thresholds.tolist(),
                "correctCounts": correct_counts,
                "incorrectCounts": incorrect_counts,
                "noPredictionCounts": no_pred_counts,
            }
        return out

    def evaluate_all(self, ds, label_col=None, prediction_col=None) -> Dict[str, Any]:
        y, pred = self._extract(ds, label_col, prediction_col)
        return self.evaluate_arrays(y, pred.prediction, pred.probability)


class OpBinScoreEvaluator(OpEvaluatorBase):
    """Calibration-bin metrics (OpBinScoreEvaluator.scala:53)."""

    name = "binScoreEval"
    default_metric = "BrierScore"
    is_larger_better = False

    def __init__(self, label_col: Optional[str] = None, prediction_col: Optional[str] = None,
                 num_bins: int = 100):
        super().__init__(label_col, prediction_col)
        self.num_bins = num_bins

    def evaluate_arrays(self, y, prediction, probability=None) -> Dict[str, Any]:
        y = np.asarray(y, dtype=np.float64)
        score = np.asarray(probability[:, 1] if probability is not None and probability.ndim == 2
                           else prediction, dtype=np.float64)
        brier = float(np.mean((score - y) ** 2)) if len(y) else 0.0
        edges = np.linspace(0.0, 1.0, self.num_bins + 1)
        idx = np.clip(np.digitize(score, edges) - 1, 0, self.num_bins - 1)
        counts = np.bincount(idx, minlength=self.num_bins).astype(float)
        avg_score = np.zeros(self.num_bins)
        avg_conv = np.zeros(self.num_bins)
        for b in range(self.num_bins):
            m = idx == b
            if m.any():
                avg_score[b] = score[m].mean()
                avg_conv[b] = y[m].mean()
        return {
            "BrierScore": brier,
            "binCenters": ((edges[:-1] + edges[1:]) / 2).tolist(),
            "numberOfDataPoints": counts.tolist(),
            "averageScore": avg_score.tolist(),
            "averageConversionRate": avg_conv.tolist(),
        }

    def evaluate_all(self, ds, label_col=None, prediction_col=None) -> Dict[str, Any]:
        y, pred = self._extract(ds, label_col, prediction_col)
        return self.evaluate_arrays(y, pred.prediction, pred.probability)


class OpLogLoss(OpEvaluatorBase):
    """Multiclass log loss (impl/evaluator/OPLogLoss.scala)."""

    name = "logLoss"
    default_metric = "LogLoss"
    is_larger_better = False

    def evaluate_arrays(self, y, prediction, probability=None) -> Dict[str, Any]:
        y = np.asarray(y, dtype=np.int64)
        if probability is None:
            raise ValueError("LogLoss requires probabilities")
        p = np.clip(probability[np.arange(len(y)), y], 1e-15, 1.0)
        return {"LogLoss": float(-np.mean(np.log(p)))}

    def evaluate_all(self, ds, label_col=None, prediction_col=None) -> Dict[str, Any]:
        y, pred = self._extract(ds, label_col, prediction_col)
        return self.evaluate_arrays(y, pred.prediction, pred.probability)
