"""Package."""
