"""cli — project generator (reference cli/ module, `transmogrifai gen`).

Usage:
    python -m transmogrifai_tpu.cli gen --input data.csv --response y \
        --id id_col MyProject
"""
from .gen import (FieldSchema, ProblemKind, generate_project, infer_field,
                  infer_problem_kind, infer_schema)

__all__ = ["FieldSchema", "ProblemKind", "generate_project", "infer_field",
           "infer_problem_kind", "infer_schema"]
