"""CLI entry: python -m transmogrifai_tpu.cli gen ... (cli/.../CLI.scala)."""
import argparse
import sys

from .gen import generate_project


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve":
        # delegates to the standalone server entry (own argparse/flags)
        from .serve import main as serve_main

        return serve_main(argv[1:])
    p = argparse.ArgumentParser(prog="transmogrifai_tpu.cli")
    sub = p.add_subparsers(dest="command", required=True)
    sub.add_parser("serve", help="serve a saved model over HTTP "
                                 "(see transmogrifai-tpu-serve --help)")
    gen = sub.add_parser("gen", help="generate a runnable project from a CSV")
    gen.add_argument("project", help="project name / output directory")
    gen.add_argument("--input", required=True, help="training CSV path")
    gen.add_argument("--response", required=True, help="response column")
    gen.add_argument("--id", dest="id_field", help="row-id column")
    gen.add_argument("--output", help="output directory (default: project name)")
    args = p.parse_args(argv)
    if args.command == "gen":
        out = generate_project(args.project, args.input, args.response,
                               id_field=args.id_field, out_dir=args.output)
        print(f"Generated project at {out}")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
