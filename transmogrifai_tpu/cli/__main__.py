"""CLI entry: python -m transmogrifai_tpu.cli gen ... (cli/.../CLI.scala)."""
import argparse
import sys

from .gen import generate_project


def main(argv=None):
    p = argparse.ArgumentParser(prog="transmogrifai_tpu.cli")
    sub = p.add_subparsers(dest="command", required=True)
    gen = sub.add_parser("gen", help="generate a runnable project from a CSV")
    gen.add_argument("project", help="project name / output directory")
    gen.add_argument("--input", required=True, help="training CSV path")
    gen.add_argument("--response", required=True, help="response column")
    gen.add_argument("--id", dest="id_field", help="row-id column")
    gen.add_argument("--output", help="output directory (default: project name)")
    args = p.parse_args(argv)
    if args.command == "gen":
        out = generate_project(args.project, args.input, args.response,
                               id_field=args.id_field, out_dir=args.output)
        print(f"Generated project at {out}")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
