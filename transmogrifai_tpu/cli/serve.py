"""transmogrifai-tpu-serve — serve a saved OpWorkflowModel over HTTP.

Standalone entry (no OpApp subclass needed): point it at a model directory
produced by ``model.save(...)`` / a Train run and it loads, warms every
shape bucket, and serves::

    transmogrifai-tpu-serve /path/to/model --port 8123
    curl -s localhost:8123/score -d '{"x": 1.5, "cat": "a"}'
    curl -s localhost:8123/metrics

Hot-swap a retrained model without dropping requests::

    curl -s localhost:8123/models -d '{"path": "/path/to/model_v2"}'
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="transmogrifai-tpu-serve",
        description="Micro-batched online scoring server for a saved model")
    p.add_argument("model", help="saved model directory (model.save output)")
    p.add_argument("--version", default=None, help="version label (default v1)")
    p.add_argument("--tenant", default=None,
                   help="deploy as this named tenant on the shared plane "
                        "(score with ?tenant=NAME; default: the single "
                        "anonymous tenant)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8123)
    p.add_argument("--max-batch", type=int, default=64,
                   help="largest micro-batch / shape bucket")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="max time a request waits for batchmates")
    p.add_argument("--queue-size", type=int, default=1024,
                   help="admission queue bound (beyond it: HTTP 429)")
    p.add_argument("--replicas", type=int, default=None,
                   help="per-chip model replicas (default: "
                        "TMOG_SERVE_REPLICAS or one per device)")
    p.add_argument("--duration", type=float, default=None,
                   help="seconds to serve (default: until Ctrl-C)")
    args = p.parse_args(argv)

    from ..utils.backend import ensure_backend

    platform, fallback = ensure_backend()
    if fallback:
        print(f"transmogrifai-tpu-serve: falling back to {platform} "
              f"({fallback})", file=sys.stderr)

    from ..serve import ModelRegistry, ModelServer
    from ..workflow.model import load_model

    registry = ModelRegistry(max_batch=args.max_batch,
                             replicas=args.replicas)
    server = ModelServer(registry, host=args.host, port=args.port,
                         max_batch=args.max_batch,
                         max_wait_ms=args.max_wait_ms,
                         queue_size=args.queue_size)
    print(f"Loading model from {args.model} ...", file=sys.stderr)
    if args.tenant:
        entry = registry.deploy(load_model(args.model), version=args.version,
                                tenant=args.tenant)
    else:
        entry = registry.deploy(load_model(args.model), version=args.version)
    who = f" (tenant {args.tenant})" if args.tenant else ""
    print(f"Deployed {entry.version}{who} (warmed buckets: {entry.buckets}, "
          f"replicas: {len(entry.replicas)})", file=sys.stderr)
    server.start()
    print(f"Serving at {server.url}/score (metrics: {server.url}/metrics)",
          file=sys.stderr)
    try:
        server.wait(args.duration)
    finally:
        server.stop()
        snap = server.metrics.snapshot()
        print(f"Served {snap['responses']} responses "
              f"({snap['shed']} shed, {snap['errors']} errors)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
