"""DSL — rich methods installed on ``Feature`` (the syntax layer).

Reference parity: core/src/main/scala/com/salesforce/op/dsl/ — the implicit
classes ``RichNumericFeature`` (arithmetic, vectorize, autoBucketize,
zNormalize), ``RichTextFeature`` (tokenize, pivot, smartVectorize),
``RichFeature`` (alias, map, filter, replaceWith, exists, toOccur),
``RichVectorFeature`` (sanityCheck, combine), ``RichDateFeature``,
``RichFeaturesCollection`` (transmogrify).

Python has no implicits; instead the methods are installed directly on the
``Feature`` class when this module imports (the package ``__init__`` imports
it, so ``from transmogrifai_tpu import *`` gives the full DSL).  Operator
overloads make ``(sib_sp + par_ch + 1).alias("family_size")`` work exactly
like the reference's Titanic example (OpTitanicSimple.scala:77-130).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence, Type

from . import types as T
from .features.feature import Feature
from .impl.feature.bucketizers import DecisionTreeNumericBucketizer, NumericBucketizer
from .impl.feature.dates import DateListVectorizer, DateToUnitCircleTransformer, TimePeriod
from .impl.feature.detectors import (EmailToPickList, HumanNameDetector,
                                     MimeTypeDetector, NameEntityRecognizer,
                                     PhoneNumberParser, UrlToPickList,
                                     ValidEmailTransformer)
from .impl.feature.dates import TimePeriodTransformer
from .impl.feature.scalers import (DescalerTransformer,
                                   IsotonicRegressionCalibrator,
                                   OpScalarStandardScaler,
                                   PercentileCalibrator, ScalerTransformer,
                                   ScalingType)
from .impl.feature.smart_text import SmartTextVectorizer
from .impl.feature.text import (JaccardSimilarity, LangDetector,
                                NGramSimilarity, OpCountVectorizer,
                                OpIndexToString, OpNGram, OpStopWordsRemover,
                                TextLenTransformer, TextTokenizer)
from .impl.feature.transformers import (AddTransformer, AliasTransformer,
                                        DivideTransformer, ExistsTransformer,
                                        FillMissingWithMean, FilterTransformer,
                                        LambdaTransformer, MultiplyTransformer,
                                        ReplaceTransformer, ScalarMathTransformer,
                                        SubtractTransformer, ToOccurTransformer)
from .impl.feature.transmogrifier import transmogrify
from .impl.feature.vectorizers import OneHotVectorizer, VectorsCombiner


def _unary(stage, feature: Feature) -> Feature:
    return stage.set_input(feature).get_output()


def _binary_math(stage_cls, scalar_op: str):
    def method(self: Feature, other):
        if isinstance(other, Feature):
            return stage_cls().set_input(self, other).get_output()
        if not isinstance(other, (int, float)):
            return NotImplemented
        return _unary(ScalarMathTransformer(scalar_op, float(other)), self)
    return method


def _r_scalar(op: str):
    def method(self: Feature, other):
        if not isinstance(other, (int, float)):
            return NotImplemented
        return _unary(ScalarMathTransformer(op, float(other)), self)
    return method


# ---------------------------------------------------------------------------
# generic (RichFeature)
# ---------------------------------------------------------------------------
def alias(self: Feature, name: str) -> Feature:
    return _unary(AliasTransformer(name), self)


def map_fn(self: Feature, fn: Callable, output_type: Type[T.FeatureType]) -> Feature:
    return _unary(LambdaTransformer(fn, self.ftype, output_type), self)


def filter_by(self: Feature, predicate: Callable[[Any], bool]) -> Feature:
    return _unary(FilterTransformer(predicate, self.ftype), self)


def replace_with(self: Feature, match_value: Any, replace_value: Any) -> Feature:
    return _unary(ReplaceTransformer(match_value, replace_value, self.ftype), self)


def exists(self: Feature) -> Feature:
    return _unary(ExistsTransformer(self.ftype), self)


def occurs(self: Feature) -> Feature:
    return _unary(ToOccurTransformer(self.ftype), self)


# ---------------------------------------------------------------------------
# numeric (RichNumericFeature)
# ---------------------------------------------------------------------------
def vectorize(self: Feature, *others: Feature, label: Optional[Feature] = None,
              **kw) -> Feature:
    """Type-default vectorization of this + optionally more features
    (RichFeature.vectorize / transmogrify on one group)."""
    return transmogrify([self, *others], label=label, **kw)


def auto_bucketize(self: Feature, label: Feature, **kw) -> Feature:
    """Label-aware bucketing (RichNumericFeature.autoBucketize)."""
    return DecisionTreeNumericBucketizer(**kw).set_input(label, self).get_output()


def bucketize(self: Feature, splits: Sequence[float], **kw) -> Feature:
    return _unary(NumericBucketizer(splits=splits, **kw), self)


def z_normalize(self: Feature) -> Feature:
    """RichNumericFeature.zNormalize."""
    return _unary(OpScalarStandardScaler(), self)


def fill_missing_with_mean(self: Feature, default: float = 0.0) -> Feature:
    return _unary(FillMissingWithMean(default=default), self)


def _scalar_math(op: str):
    """No-argument unary math method (abs/exp/sqrt/ceil/floor — their
    transformer ignores the scalar, so the DSL does not accept one)."""

    def method(self: Feature) -> Feature:
        return _unary(ScalarMathTransformer(op, 0.0), self)

    method.__name__ = op
    method.__doc__ = f"RichNumericFeature.{op} (ScalarMathTransformer)."
    return method


def power(self: Feature, exponent: float = 2.0) -> Feature:
    """RichNumericFeature.power:228."""
    return _unary(ScalarMathTransformer("power", exponent), self)


def round_(self: Feature, digits: int = 0) -> Feature:
    """RichNumericFeature.round:193-200 — half-up; digit-less rounds to
    Integral, round(digits) stays Real."""
    return _unary(ScalarMathTransformer("round", float(digits)), self)


def log_base(self: Feature, base: float = math.e) -> Feature:
    """RichNumericFeature.log(base):221 — ln(v) / ln(base) via the natural
    log transformer composed with a scalar multiply."""
    ln = _unary(ScalarMathTransformer("log", 0.0), self)
    if abs(base - math.e) < 1e-12:
        return ln
    return _unary(ScalarMathTransformer("multiply", 1.0 / math.log(base)), ln)


def scale(self: Feature, scaling_type=None, slope: float = 1.0,
          intercept: float = 0.0) -> Feature:
    """Invertible scaling (RichNumericFeature.scale:347); pair with
    ``descale``."""
    st = scaling_type if scaling_type is not None else ScalingType.Linear
    return _unary(ScalerTransformer(scaling_type=st, slope=slope,
                                    intercept=intercept), self)


def descale(self: Feature, scaled: Feature) -> Feature:
    """Invert a sibling ``scale`` using its recorded scaler args
    (RichNumericFeature.descale:362): ``value.descale(scaled_origin)``."""
    return DescalerTransformer().set_input(self, scaled).get_output()


def to_percentile(self: Feature, buckets: int = 100) -> Feature:
    """RichNumericFeature.toPercentile:387 (PercentileCalibrator)."""
    return _unary(PercentileCalibrator(buckets=buckets), self)


def to_isotonic_calibrated(self: Feature, label: Feature) -> Feature:
    """RichNumericFeature.toIsotonicCalibrated:398."""
    return IsotonicRegressionCalibrator().set_input(label, self).get_output()


def deindexed(self: Feature, labels: Sequence[str]) -> Feature:
    """Index -> original string label (RichNumericFeature.deindexed:418)."""
    return _unary(OpIndexToString(labels=list(labels)), self)


def to_time_period(self: Feature, time_period=None) -> Feature:
    """Date -> calendar period ordinal (RichDateFeature.toTimePeriod)."""
    tp = time_period if time_period is not None else TimePeriod.DayOfWeek
    return _unary(TimePeriodTransformer(time_period=tp), self)


def ngram_similarity(self: Feature, other: Feature, n: int = 3) -> Feature:
    """Char-ngram Jaccard of two text features (RichTextFeature)."""
    return NGramSimilarity(n=n).set_input(self, other).get_output()


def jaccard_similarity(self: Feature, other: Feature) -> Feature:
    """Token-set Jaccard of two MultiPickList features (RichSetFeature)."""
    return JaccardSimilarity().set_input(self, other).get_output()


# ---------------------------------------------------------------------------
# text (RichTextFeature)
# ---------------------------------------------------------------------------
def tokenize(self: Feature, **kw) -> Feature:
    return _unary(TextTokenizer(**kw), self)


def smart_vectorize(self: Feature, *others: Feature, **kw) -> Feature:
    return SmartTextVectorizer(**kw).set_input(self, *others).get_output()


def pivot(self: Feature, *others: Feature, top_k: int = 20, min_support: int = 10,
          **kw) -> Feature:
    """Categorical one-hot pivot (RichTextFeature.pivot)."""
    return OneHotVectorizer(top_k=top_k, min_support=min_support, **kw) \
        .set_input(self, *others).get_output()


def detect_languages(self: Feature) -> Feature:
    return _unary(LangDetector(), self)


def text_len(self: Feature) -> Feature:
    return _unary(TextLenTransformer(), self)


def remove_stop_words(self: Feature, **kw) -> Feature:
    return _unary(OpStopWordsRemover(**kw), self)


def ngram(self: Feature, n: int = 2) -> Feature:
    return _unary(OpNGram(n=n), self)


def count_vectorize(self: Feature, **kw) -> Feature:
    return _unary(OpCountVectorizer(**kw), self)


def is_valid_email(self: Feature) -> Feature:
    return _unary(ValidEmailTransformer(), self)


def to_email_domain(self: Feature) -> Feature:
    return _unary(EmailToPickList(), self)


def to_url_host(self: Feature) -> Feature:
    return _unary(UrlToPickList(), self)


def is_valid_phone(self: Feature, region: str = "US") -> Feature:
    return _unary(PhoneNumberParser(region=region), self)


def detect_mime_types(self: Feature) -> Feature:
    return _unary(MimeTypeDetector(), self)


def detect_names(self: Feature) -> Feature:
    return _unary(HumanNameDetector(), self)


def recognize_entities(self: Feature) -> Feature:
    return _unary(NameEntityRecognizer(), self)


# ---------------------------------------------------------------------------
# dates (RichDateFeature)
# ---------------------------------------------------------------------------
def to_unit_circle(self: Feature, time_period: TimePeriod = TimePeriod.HourOfDay) -> Feature:
    return _unary(DateToUnitCircleTransformer(time_period=time_period), self)


def vectorize_date_list(self: Feature, **kw) -> Feature:
    return _unary(DateListVectorizer(**kw), self)


# ---------------------------------------------------------------------------
# vector (RichVectorFeature)
# ---------------------------------------------------------------------------
def sanity_check(self: Feature, label: Feature, **kw) -> Feature:
    """RichVectorFeature.sanityCheck — label-aware feature QA."""
    from .impl.preparators.sanity_checker import SanityChecker

    return SanityChecker(**kw).set_input(label, self).get_output()


def combine(self: Feature, *others: Feature) -> Feature:
    return VectorsCombiner().set_input(self, *others).get_output()


_METHODS = {
    # generic
    "alias": alias, "map": map_fn, "filter": filter_by, "replace_with": replace_with,
    "exists": exists, "occurs": occurs,
    # numeric
    "vectorize": vectorize, "auto_bucketize": auto_bucketize, "bucketize": bucketize,
    "z_normalize": z_normalize, "fill_missing_with_mean": fill_missing_with_mean,
    "abs": _scalar_math("abs"), "exp": _scalar_math("exp"),
    "sqrt": _scalar_math("sqrt"), "log": log_base,
    "power": power, "ceil": _scalar_math("ceil"),
    "floor": _scalar_math("floor"), "round": round_,
    "scale": scale, "descale": descale, "to_percentile": to_percentile,
    "to_isotonic_calibrated": to_isotonic_calibrated, "deindexed": deindexed,
    "to_time_period": to_time_period,
    "ngram_similarity": ngram_similarity,
    "jaccard_similarity": jaccard_similarity,
    # text
    "tokenize": tokenize, "smart_vectorize": smart_vectorize, "pivot": pivot,
    "detect_languages": detect_languages, "text_len": text_len,
    "remove_stop_words": remove_stop_words, "ngram": ngram,
    "count_vectorize": count_vectorize, "is_valid_email": is_valid_email,
    "to_email_domain": to_email_domain, "to_url_host": to_url_host,
    "is_valid_phone": is_valid_phone, "detect_mime_types": detect_mime_types,
    "detect_names": detect_names, "recognize_entities": recognize_entities,
    # dates
    "to_unit_circle": to_unit_circle, "vectorize_date_list": vectorize_date_list,
    # vector
    "sanity_check": sanity_check, "combine": combine,
    # operators
    "__add__": _binary_math(AddTransformer, "plus"),
    "__sub__": _binary_math(SubtractTransformer, "minus"),
    "__mul__": _binary_math(MultiplyTransformer, "multiply"),
    "__truediv__": _binary_math(DivideTransformer, "divide"),
    "__radd__": _r_scalar("plus"),
    "__rsub__": _r_scalar("rminus"),
    "__rmul__": _r_scalar("multiply"),
    "__rtruediv__": _r_scalar("rdivide"),
}


def install() -> None:
    """Install the DSL methods on Feature (idempotent)."""
    for name, fn in _METHODS.items():
        setattr(Feature, name, fn)


install()
