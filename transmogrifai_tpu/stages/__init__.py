"""Package."""
