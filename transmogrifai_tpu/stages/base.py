"""Stage contracts — the estimator/transformer abstractions.

Reference parity: features/src/main/scala/com/salesforce/op/stages/OpPipelineStages.scala:55
(``OpPipelineStageBase``: operationName, setInput/getOutput, transformSchema)
and the arity traits (``OpPipelineStage1..4``, ``N``, ``2N`` — :218-523), plus
``OpTransformer`` (:526) — the row-function scoring interface.

TPU-first redesign: a stage is a pure function pair —

- ``fit(dataset) -> Model`` computes one-pass statistics host/device-side and
  returns a fitted Model whose parameters are plain arrays (pytree-friendly),
- ``Model.transform_columns(columns) -> Column`` is a pure per-batch function;
  whole DAG layers of these fuse into a single jit'd computation (the analog
  of FitStagesUtil.applyOpTransformations's fused rdd.map, FitStagesUtil.scala:96).

Row-wise scoring (``transform_row``) is derived from the batch path over
single-row columns — guaranteeing batch ≡ row parity by construction (the
property the reference asserts in every OpTransformerSpec).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type, TYPE_CHECKING

import numpy as np

from .. import types as T
from ..columns import Column, Dataset, column_from_scalars

if TYPE_CHECKING:
    from ..features.feature import Feature


_UID_LOCK = threading.Lock()
_UID_COUNTS: Dict[str, int] = {}


def make_uid(cls_name: str) -> str:
    """Reference-style stage uid: ``ClassName_<12 hex>`` (UID.scala analog).

    Deterministic — a per-class construction counter, not random hex: a
    restarted process that rebuilds the same pipeline reconstructs the SAME
    uids, so content-keyed checkpoint keys (stream chunk resume, sweep shard
    resume) survive preemption — a SIGKILLed host re-running its script
    finds its own completed work.  In-process uniqueness is unchanged (the
    counter never repeats a value for a class)."""
    with _UID_LOCK:
        n = _UID_COUNTS.get(cls_name, 0)
        _UID_COUNTS[cls_name] = n + 1
    return f"{cls_name}_{n:012x}"


class PipelineStage:
    """Base for all stages.

    A stage declares typed inputs (Features), produces one or more output
    Features, and carries serializable params.
    """

    #: number of output features this stage produces
    n_outputs: int = 1

    def __init__(self, operation_name: str, output_type: Type[T.FeatureType],
                 uid: Optional[str] = None, **params: Any):
        self.operation_name = operation_name
        self.output_type = output_type
        self.uid = uid or make_uid(type(self).__name__)
        self._params: Dict[str, Any] = dict(params)
        self.inputs: Tuple["Feature", ...] = ()
        self._outputs: Optional[List["Feature"]] = None
        #: metadata attached to output columns (summaries, vector provenance)
        self.metadata: Dict[str, Any] = {}

    # ---- params ------------------------------------------------------------
    def get_param(self, name: str, default: Any = None) -> Any:
        return self._params.get(name, default)

    def set_param(self, name: str, value: Any) -> "PipelineStage":
        self._params[name] = value
        return self

    @property
    def params(self) -> Dict[str, Any]:
        return dict(self._params)

    # ---- wiring ------------------------------------------------------------
    def set_input(self, *features: "Feature") -> "PipelineStage":
        self.check_input_types(features)
        self.inputs = tuple(features)
        self._outputs = None
        return self

    def check_input_types(self, features: Sequence["Feature"]) -> None:
        """Schema validation hook (transformSchema analog,
        OpPipelineStages.scala:112)."""

    @property
    def input_features(self) -> Tuple["Feature", ...]:
        return self.inputs

    def output_name(self, index: int = 0) -> str:
        base = "-".join(f.name for f in self.inputs) or self.operation_name
        suffix = f"_{index}" if self.n_outputs > 1 else ""
        return f"{base}_{self.operation_name}{suffix}_{self.uid.split('_')[-1]}"

    def output_is_response(self) -> bool:
        """Output is a response iff any input is (reference: OpPipelineStage
        outputIsResponse); stages with AllowLabelAsInput still produce
        predictors (OpPipelineStages.scala:203)."""
        if getattr(self, "allow_label_as_input", False):
            return False
        return any(f.is_response for f in self.inputs)

    def get_output(self) -> "Feature":
        assert self.n_outputs == 1, f"{self} has {self.n_outputs} outputs; use get_outputs()"
        return self.get_outputs()[0]

    def get_outputs(self) -> List["Feature"]:
        from ..features.feature import Feature

        if self._outputs is None:
            out_types = self.output_types()
            self._outputs = [
                Feature(
                    name=self.output_name(i),
                    ftype=out_types[i],
                    is_response=self.output_is_response(),
                    origin_stage=self,
                    parents=tuple(self.inputs),
                )
                for i in range(self.n_outputs)
            ]
        return self._outputs

    def output_types(self) -> List[Type[T.FeatureType]]:
        return [self.output_type] * self.n_outputs

    def __repr__(self) -> str:
        return f"{type(self).__name__}(uid={self.uid!r})"


class Transformer(PipelineStage):
    """A stage that needs no fitting — pure batch function.

    The batch function is the OpTransformer analog; ``transform_row`` derives
    the row function (transformKeyValue, OpPipelineStages.scala:550) from it.
    """

    def transform_columns(self, cols: Sequence[Column]) -> Column:
        raise NotImplementedError

    def transform_dataset(self, ds: Dataset) -> Column:
        return self.transform_columns([ds[f.name] for f in self.inputs])

    def transform_row(self, row: Dict[str, T.FeatureType]) -> T.FeatureType:
        cols = [column_from_scalars(f.ftype, [row[f.name]]) for f in self.inputs]
        return self.transform_columns(cols).to_scalar(0)


class Model(Transformer):
    """A fitted transformer produced by an Estimator."""

    def __init__(self, operation_name: str, output_type: Type[T.FeatureType],
                 uid: Optional[str] = None, parent_uid: Optional[str] = None, **params: Any):
        super().__init__(operation_name, output_type, uid=uid, **params)
        self.parent_uid = parent_uid


class Estimator(PipelineStage):
    """A stage that must be fitted; ``fit`` returns a Model.

    The returned model inherits the estimator's uid/inputs/outputs so the DAG
    node identity is stable across fitting (the reference swaps estimators for
    their fitted models in-place, FitStagesUtil.scala:251).
    """

    def fit_columns(self, cols: Sequence[Column], dataset: Dataset) -> Model:
        raise NotImplementedError

    def fit(self, ds: Dataset) -> Model:
        model = self.fit_columns([ds[f.name] for f in self.inputs], ds)
        model.uid = self.uid
        model.parent_uid = self.uid
        model.inputs = self.inputs
        model.operation_name = self.operation_name
        model._outputs = self._outputs
        if not model.metadata:
            model.metadata = self.metadata
        return model


class AllowLabelAsInput:
    """Marker mixin: stage may consume the label yet outputs a predictor
    (OpPipelineStages.scala:203 — used by SanityChecker, ModelSelector etc.)."""

    allow_label_as_input = True


# ---------------------------------------------------------------------------
# Arity bases (reference: stages/base/unary..quaternary, sequence)
# ---------------------------------------------------------------------------
class UnaryTransformer(Transformer):
    """1 -> 1 transformer defined by a scalar fn, vectorized over the column.

    Reference parity: base/unary/UnaryTransformer.scala:104.  Subclasses
    override either ``transform_fn`` (scalar) or ``transform_columns`` (batch,
    preferred for device execution).
    """

    def __init__(self, operation_name: str, input_type: Type[T.FeatureType],
                 output_type: Type[T.FeatureType], uid: Optional[str] = None, **params):
        super().__init__(operation_name, output_type, uid=uid, **params)
        self.input_type = input_type

    def check_input_types(self, features) -> None:
        if len(features) != 1:
            raise ValueError(f"{type(self).__name__} takes exactly 1 input")

    def transform_fn(self, value: T.FeatureType) -> T.FeatureType:
        raise NotImplementedError

    def transform_columns(self, cols: Sequence[Column]) -> Column:
        col = cols[0]
        out = [self.transform_fn(col.to_scalar(i)) for i in range(len(col))]
        return column_from_scalars(self.output_type, out)


class BinaryTransformer(Transformer):
    """(I1, I2) -> O (base/binary/BinaryTransformer.scala)."""

    def check_input_types(self, features) -> None:
        if len(features) != 2:
            raise ValueError(f"{type(self).__name__} takes exactly 2 inputs")

    def transform_fn(self, a: T.FeatureType, b: T.FeatureType) -> T.FeatureType:
        raise NotImplementedError

    def transform_columns(self, cols: Sequence[Column]) -> Column:
        a, b = cols
        out = [self.transform_fn(a.to_scalar(i), b.to_scalar(i)) for i in range(len(a))]
        return column_from_scalars(self.output_type, out)


class TernaryTransformer(Transformer):
    def check_input_types(self, features) -> None:
        if len(features) != 3:
            raise ValueError(f"{type(self).__name__} takes exactly 3 inputs")

    def transform_fn(self, a, b, c) -> T.FeatureType:
        raise NotImplementedError

    def transform_columns(self, cols: Sequence[Column]) -> Column:
        a, b, c = cols
        out = [self.transform_fn(a.to_scalar(i), b.to_scalar(i), c.to_scalar(i))
               for i in range(len(a))]
        return column_from_scalars(self.output_type, out)


class QuaternaryTransformer(Transformer):
    def check_input_types(self, features) -> None:
        if len(features) != 4:
            raise ValueError(f"{type(self).__name__} takes exactly 4 inputs")

    def transform_fn(self, a, b, c, d) -> T.FeatureType:
        raise NotImplementedError

    def transform_columns(self, cols: Sequence[Column]) -> Column:
        a, b, c, d = cols
        out = [self.transform_fn(a.to_scalar(i), b.to_scalar(i), c.to_scalar(i), d.to_scalar(i))
               for i in range(len(a))]
        return column_from_scalars(self.output_type, out)


class SequenceTransformer(Transformer):
    """N homogeneous inputs -> 1 output (base/sequence/)."""

    def check_input_types(self, features) -> None:
        if len(features) < 1:
            raise ValueError(f"{type(self).__name__} takes at least 1 input")


class UnaryEstimator(Estimator):
    """1 -> 1 estimator (base/unary/UnaryEstimator.scala:56)."""

    def __init__(self, operation_name: str, input_type: Type[T.FeatureType],
                 output_type: Type[T.FeatureType], uid: Optional[str] = None, **params):
        super().__init__(operation_name, output_type, uid=uid, **params)
        self.input_type = input_type

    def check_input_types(self, features) -> None:
        if len(features) != 1:
            raise ValueError(f"{type(self).__name__} takes exactly 1 input")


class BinaryEstimator(Estimator):
    def check_input_types(self, features) -> None:
        if len(features) != 2:
            raise ValueError(f"{type(self).__name__} takes exactly 2 inputs")


class SequenceEstimator(Estimator):
    """N homogeneous inputs -> 1 output (base/sequence/SequenceEstimator.scala:57)."""

    def check_input_types(self, features) -> None:
        if len(features) < 1:
            raise ValueError(f"{type(self).__name__} takes at least 1 input")


class BinarySequenceEstimator(Estimator):
    """1 fixed input + N homogeneous inputs (base/sequence/BinarySequenceEstimator)."""

    def check_input_types(self, features) -> None:
        if len(features) < 2:
            raise ValueError(f"{type(self).__name__} takes at least 2 inputs")
