"""JVM <-> JAX bridge: drive the TPU runtime from the Scala OpWorkflow facade.

North star (BASELINE.json): the reference's Scala entrypoint
``OpWorkflow().train()`` (OpWorkflow.scala:61,347) drives a TPU pod through
this bridge — Arrow IPC data frames + JSON control frames over TCP.  The JVM
half lives in ``bridge/scala/``; ``client.py`` is its tested Python twin.
"""
from .client import BridgeClient
from .server import serve

__all__ = ["BridgeClient", "serve"]
