"""Python bridge client — mirrors the Scala facade 1:1 (and tests it).

The Scala source under ``bridge/scala/`` implements exactly this sequence
with ``org.apache.arrow.vector`` + ``java.net.Socket``; keeping a Python
twin means the protocol is covered by tests/test_bridge.py even though this
image has no JVM to compile the Scala half.
"""
from __future__ import annotations

import socket
from typing import Any, Dict, Optional

from . import protocol as P


class BridgeClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 7099):
        self.sock = socket.create_connection((host, port))

    # ---- plumbing -----------------------------------------------------------
    def _call(self, req: Dict[str, Any], table=None, expect_arrow: bool = False):
        if table is not None:
            P.send_arrow(self.sock, table)
        P.send_json(self.sock, req)
        result_table = None
        if expect_arrow:
            kind, payload = P.recv_frame(self.sock)
            if kind == P.KIND_ARROW:
                result_table = P.parse_arrow(payload)
                resp = P.recv_json(self.sock)
            else:  # error came back instead of data
                import json as _json

                resp = _json.loads(payload.decode("utf-8"))
        else:
            resp = P.recv_json(self.sock)
        if not resp.get("ok"):
            raise RuntimeError(f"bridge error: {resp.get('error')}\n"
                               f"{resp.get('traceback', '')}")
        return (resp, result_table) if expect_arrow else resp

    # ---- the OpWorkflow facade surface --------------------------------------
    def ping(self) -> Dict[str, Any]:
        return self._call({"op": "ping"})

    def put_data(self, name: str, df) -> Dict[str, Any]:
        import pyarrow as pa

        return self._call({"op": "put_data", "name": name},
                          table=pa.Table.from_pandas(df))

    def build(self, spec: Dict[str, Any], name: str = "wf") -> Dict[str, Any]:
        return self._call({"op": "build", "name": name, "spec": spec})

    def train(self, data: str, workflow: str = "wf", model: str = "model",
              key: Optional[str] = None) -> Dict[str, Any]:
        req = {"op": "train", "workflow": workflow, "data": data, "model": model}
        if key:
            req["key"] = key
        return self._call(req)

    def score(self, data: str, model: str = "model"):
        resp, table = self._call({"op": "score", "model": model, "data": data},
                                 expect_arrow=True)
        return table

    def evaluate(self, data: str, label: str, model: str = "model",
                 evaluator: str = "binary") -> Dict[str, float]:
        return self._call({"op": "evaluate", "model": model, "data": data,
                           "label": label, "evaluator": evaluator})["metrics"]

    def save(self, path: str, model: str = "model") -> None:
        self._call({"op": "save", "model": model, "path": path})

    def load(self, path: str, model: str = "model") -> None:
        self._call({"op": "load", "model": model, "path": path})

    def summary(self, model: str = "model") -> Dict[str, Any]:
        return self._call({"op": "summary", "model": model})["summary"]

    def shutdown(self) -> None:
        P.send_json(self.sock, {"op": "shutdown"})
        P.recv_json(self.sock)
        self.sock.close()

    def close(self) -> None:
        self.sock.close()
