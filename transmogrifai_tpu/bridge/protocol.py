"""Wire protocol of the JVM <-> JAX bridge (BASELINE north star).

The reference's user entrypoint is the Scala ``OpWorkflow().train()``
(core/src/main/scala/com/salesforce/op/OpWorkflow.scala:61,347).  To drive
this TPU runtime from that surface WITHOUT Spark in the loop, the bridge
speaks a deliberately boring protocol any JVM (or C++) client can implement
with zero exotic dependencies:

- transport: one TCP connection per session,
- framing: every message is ``[1-byte kind][4-byte big-endian length][payload]``,
  - kind ``J``: UTF-8 JSON control message ``{"op": ..., ...}``,
  - kind ``A``: Arrow IPC *stream* bytes (the lingua franca between JVM
    ``org.apache.arrow.vector`` and Python ``pyarrow``),
- every request gets exactly one JSON response frame (``{"ok": true, ...}``
  or ``{"ok": false, "error": ...}``), optionally preceded by one Arrow
  frame when the op returns data (``score``/``compute``).

Ops (mirroring OpWorkflowRunner's run types, OpWorkflowRunner.scala:358):

  put_data    {name}                + Arrow frame    -> stores a dataset
  build       {spec}                                 -> materialize workflow
  train       {workflow}                             -> fit, returns summary
  score       {model, data}                          -> Arrow frame + json
  evaluate    {model, data, evaluator}               -> metrics json
  save        {model, path} / load {path}            -> model persistence
  summary     {model}                                -> ModelSelector summary
  shutdown    {}                                     -> server exits

The workflow ``spec`` is declarative (no pickled closures — SURVEY §7
"Serialization" hard part): features by (name, type, field, response) and
stages by (class path, params, input feature names); see bridge/spec.py.
"""
from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, Optional, Tuple

KIND_JSON = b"J"
KIND_ARROW = b"A"

_HEADER = struct.Struct(">cI")
MAX_FRAME = 1 << 31


def send_frame(sock: socket.socket, kind: bytes, payload: bytes) -> None:
    sock.sendall(_HEADER.pack(kind, len(payload)))
    sock.sendall(payload)


def send_json(sock: socket.socket, obj: Dict[str, Any]) -> None:
    send_frame(sock, KIND_JSON, json.dumps(obj).encode("utf-8"))


def send_arrow(sock: socket.socket, table) -> None:
    import pyarrow as pa

    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    send_frame(sock, KIND_ARROW, sink.getvalue().to_pybytes())


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("bridge peer closed the connection")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Tuple[bytes, bytes]:
    kind, length = _HEADER.unpack(_read_exact(sock, _HEADER.size))
    if length >= MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds MAX_FRAME")
    return kind, _read_exact(sock, length)


def recv_json(sock: socket.socket) -> Dict[str, Any]:
    kind, payload = recv_frame(sock)
    if kind != KIND_JSON:
        raise ValueError(f"expected JSON frame, got {kind!r}")
    return json.loads(payload.decode("utf-8"))


def parse_arrow(payload: bytes):
    import pyarrow as pa

    with pa.ipc.open_stream(pa.BufferReader(payload)) as r:
        return r.read_all()
