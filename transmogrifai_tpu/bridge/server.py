"""Bridge server: drives the TPU runtime for a JVM (or any) client.

One session per connection; state is per-connection (datasets, workflows,
fitted models).  The server is the TPU-side half of the north-star picture
(BASELINE.json): the Scala ``OpWorkflow.train()`` facade in
``bridge/scala/`` connects here, ships data as Arrow, and drives
train/score/save/load — no Spark, no JVM on this side.

Run standalone:  ``python -m transmogrifai_tpu.bridge.server --port 7099``
"""
from __future__ import annotations

import argparse
import logging
import socket
import threading
import traceback
from typing import Any, Dict, Optional

import numpy as np

from . import protocol as P

log = logging.getLogger(__name__)


class BridgeSession:
    """Per-connection state + op dispatch."""

    def __init__(self):
        self.datasets: Dict[str, Any] = {}     # name -> pandas.DataFrame
        self.workflows: Dict[str, Any] = {}    # name -> OpWorkflow
        self.models: Dict[str, Any] = {}       # name -> OpWorkflowModel
        self.result_names: Dict[str, list] = {}

    # ---- ops ---------------------------------------------------------------
    def op_put_data(self, req, arrow_table):
        if arrow_table is None:
            raise ValueError("put_data requires an Arrow frame")
        self.datasets[req["name"]] = arrow_table.to_pandas()
        return {"rows": arrow_table.num_rows, "cols": arrow_table.num_columns}

    def op_build(self, req, _):
        from .spec import build_workflow

        wf = build_workflow(req["spec"])
        name = req.get("name", "wf")
        self.workflows[name] = wf
        self.result_names[name] = [f.name for f in wf.result_features]
        return {"workflow": name, "resultFeatures": self.result_names[name]}

    def op_train(self, req, _):
        wf = self.workflows[req.get("workflow", "wf")]
        df = self.datasets[req["data"]]
        key = req.get("key")
        wf.set_input_dataset(df, key=key) if key else wf.set_input_dataset(df)
        model = wf.train()
        name = req.get("model", "model")
        self.models[name] = model
        return {"model": name,
                "resultFeatures": [f.name for f in model.result_features]}

    def _scores_table(self, model, df):
        import pyarrow as pa

        scored = model.score(df)
        cols: Dict[str, Any] = {}
        for f in model.result_features:
            col = scored[f.name]
            if hasattr(col, "prediction"):  # Prediction triple
                cols[f"{f.name}.prediction"] = np.asarray(col.prediction,
                                                          np.float64)
                prob = getattr(col, "probability", None)
                if prob is not None:
                    p = np.asarray(prob, np.float64)
                    for j in range(p.shape[1]):
                        cols[f"{f.name}.probability_{j}"] = p[:, j]
            elif hasattr(col, "mask"):
                cols[f.name] = np.where(col.mask, col.values, np.nan)
            else:
                cols[f.name] = np.asarray(col.values)
        return pa.table(cols)

    def op_score(self, req, _):
        model = self.models[req.get("model", "model")]
        df = self.datasets[req["data"]]
        return {"rows": len(df)}, self._scores_table(model, df)

    def op_evaluate(self, req, _):
        from ..evaluators import (OpBinaryClassificationEvaluator,
                                  OpMultiClassificationEvaluator,
                                  OpRegressionEvaluator)

        model = self.models[req.get("model", "model")]
        kind = req.get("evaluator", "binary")
        pred_name = model.result_features[0].name
        ev = {"binary": OpBinaryClassificationEvaluator,
              "multiclass": OpMultiClassificationEvaluator,
              "regression": OpRegressionEvaluator}[kind](
            label_col=req["label"], prediction_col=pred_name)
        # evaluate on the NAMED dataset — without it the model silently
        # re-evaluates its training data and held-out metrics lie
        data = self.datasets[req["data"]] if req.get("data") else None
        metrics = model.evaluate(ev, data=data)
        return {"metrics": {k: v for k, v in metrics.items()
                            if isinstance(v, (int, float, str))}}

    def op_save(self, req, _):
        self.models[req.get("model", "model")].save(req["path"])
        return {"path": req["path"]}

    def op_load(self, req, _):
        from ..workflow.model import OpWorkflowModel

        model = OpWorkflowModel.load(req["path"])
        name = req.get("model", "model")
        self.models[name] = model
        return {"model": name}

    def op_summary(self, req, _):
        model = self.models[req.get("model", "model")]
        return {"summary": model.summary()}

    def op_ping(self, req, _):
        import jax

        return {"backend": jax.default_backend(),
                "devices": len(jax.devices())}


def _handle_connection(conn: socket.socket) -> bool:
    """Serve one session; returns True if a shutdown was requested."""
    session = BridgeSession()
    pending_arrow = None
    with conn:
        while True:
            try:
                kind, payload = P.recv_frame(conn)
            except (ConnectionError, OSError, ValueError):
                # peer closed, or a malformed/oversized frame header: drop
                # the session without allocating; the accept loop lives on
                return False
            if kind == P.KIND_ARROW:
                pending_arrow = P.parse_arrow(payload)
                continue
            req = __import__("json").loads(payload.decode("utf-8"))
            op = req.get("op", "")
            if op == "shutdown":
                P.send_json(conn, {"ok": True})
                return True
            handler = getattr(session, f"op_{op}", None)
            if handler is None:
                P.send_json(conn, {"ok": False, "error": f"unknown op {op!r}"})
                pending_arrow = None
                continue
            try:
                out = handler(req, pending_arrow)
                if isinstance(out, tuple):  # (json, arrow) response pair
                    resp, table = out
                    P.send_arrow(conn, table)
                else:
                    resp = out
                P.send_json(conn, {"ok": True, **(resp or {})})
            except Exception as e:  # surface the error to the client
                log.warning("bridge op %s failed: %s", op, e)
                P.send_json(conn, {"ok": False, "error": f"{type(e).__name__}: {e}",
                                   "traceback": traceback.format_exc(limit=8)})
            pending_arrow = None


def serve(host: str = "127.0.0.1", port: int = 7099,
          ready: Optional[threading.Event] = None) -> int:
    """Accept loop; returns the bound port (0 requests an ephemeral port)."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(4)
    bound = srv.getsockname()[1]
    if ready is not None:
        ready.port = bound  # type: ignore[attr-defined]
        ready.set()
    log.info("bridge listening on %s:%d", host, bound)
    try:
        while True:
            conn, _ = srv.accept()
            if _handle_connection(conn):
                return bound
    finally:
        srv.close()


def main():
    ap = argparse.ArgumentParser(description="transmogrifai_tpu bridge server")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7099)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    serve(args.host, args.port)


if __name__ == "__main__":
    main()
