"""Declarative workflow spec -> OpWorkflow (the bridge's no-closures IR).

The Scala facade cannot ship Python lambdas, so a workflow crosses the
bridge as data (the reference has the same constraint between driver and
executors and solves it with closure serialization; we solve it by making
the spec DECLARATIVE — SURVEY §7 "Serialization" hard part):

```json
{
  "features": [
    {"name": "survived", "type": "RealNN", "field": "survived", "response": true},
    {"name": "age", "type": "Real", "field": "age"}
  ],
  "stages": [
    {"cls": "impl.feature.vectorizers.RealVectorizer",
     "params": {"fill_with_mean": true}, "inputs": ["age"], "name": "nums"},
    {"cls": "impl.selector.factories.BinaryClassificationModelSelector",
     "factory": "with_cross_validation", "params": {"num_folds": 3},
     "inputs": ["survived", "nums"], "name": "pred"}
  ],
  "result": ["pred"]
}
```

``cls`` is resolved inside the ``transmogrifai_tpu`` package (absolute
dotted paths are rejected unless they stay inside the package — the bridge
must not be a remote-code-execution service); ``factory`` optionally names
a classmethod constructor.  Each stage's single output is registered under
``name`` for downstream inputs.
"""
from __future__ import annotations

import importlib
from typing import Any, Dict, List

from .. import types as T
from ..features.builder import FeatureBuilder
from ..workflow.workflow import OpWorkflow

_PKG = "transmogrifai_tpu"


def _resolve_stage_class(path: str):
    if path.startswith(_PKG + "."):
        path = path[len(_PKG) + 1:]
    mod_name, _, cls_name = path.rpartition(".")
    if not mod_name:
        raise ValueError(f"stage class {path!r} must be module-qualified")
    mod = importlib.import_module(f"{_PKG}.{mod_name}")
    return getattr(mod, cls_name)


def build_workflow(spec: Dict[str, Any]) -> OpWorkflow:
    """Materialize an OpWorkflow from a declarative spec (see module doc)."""
    by_name: Dict[str, Any] = {}
    for f in spec.get("features", []):
        ftype = getattr(T, f["type"])
        fb = FeatureBuilder(f["name"], ftype).extract(
            field=f.get("field", f["name"]))
        feat = fb.as_response() if f.get("response") else fb.as_predictor()
        by_name[f["name"]] = feat

    for s in spec.get("stages", []):
        cls = _resolve_stage_class(s["cls"])
        params = dict(s.get("params", {}))
        if s.get("factory"):
            stage = getattr(cls, s["factory"])(**params)
        else:
            stage = cls(**params)
        inputs = [by_name[i] for i in s["inputs"]]
        stage.set_input(*inputs)
        out = stage.get_output()
        by_name[s["name"]] = out

    results = [by_name[r] for r in spec["result"]]
    wf = OpWorkflow().set_result_features(*results)
    return wf


def list_result_names(spec: Dict[str, Any]) -> List[str]:
    return list(spec["result"])
