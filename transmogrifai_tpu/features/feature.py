"""Feature — the typed, lazy DAG node.

Reference parity: features/src/main/scala/com/salesforce/op/features/FeatureLike.scala:49.
A Feature is a *lazy pointer*: it holds its origin stage and parent features,
so the whole program is recoverable from the result features alone
(FeatureLike.scala:370 ``parentStages()``).  Graph ops implemented here:
``parent_stages`` (BFS with distances), ``raw_features``, ``traverse``,
``history``, ``same_origin``, ``copy_with_new_stages``.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Tuple, Type, TYPE_CHECKING

from .. import types as T

if TYPE_CHECKING:
    from ..stages.base import PipelineStage

_UID_COUNTER = itertools.count()


@dataclass(frozen=True)
class FeatureHistory:
    """Lineage record (reference FeatureHistory): originating raw features and
    all stages applied along the way."""

    origin_features: Tuple[str, ...]
    stages: Tuple[str, ...]

    def merge(self, other: "FeatureHistory") -> "FeatureHistory":
        return FeatureHistory(
            tuple(sorted(set(self.origin_features) | set(other.origin_features))),
            tuple(sorted(set(self.stages) | set(other.stages))),
        )


@dataclass(frozen=True, eq=False)
class Feature:
    """Typed handle to a (future) column: name, uid, response flag, origin."""

    name: str
    ftype: Type[T.FeatureType]
    is_response: bool
    origin_stage: "PipelineStage"
    parents: Tuple["Feature", ...] = ()
    # deterministic counter, not random hex: a restarted process rebuilding
    # the same DAG reconstructs the same uids, which is what lets
    # content-keyed checkpoints resume across preemptions (stages/base.py
    # make_uid has the full rationale)
    uid: str = field(
        default_factory=lambda: f"Feature_{next(_UID_COUNTER):012x}")

    # identity semantics: DAG nodes are compared by object identity (uid)
    def __eq__(self, other):
        return isinstance(other, Feature) and self.uid == other.uid

    def __hash__(self):
        return hash(self.uid)

    def __repr__(self):
        return (f"Feature(name={self.name!r}, type={self.ftype.__name__}, "
                f"response={self.is_response}, uid={self.uid!r})")

    # ---- graph properties ---------------------------------------------------
    @property
    def is_raw(self) -> bool:
        return len(self.parents) == 0

    def same_origin(self, other: "Feature") -> bool:
        """FeatureLike.scala:162 — same origin stage."""
        return self.origin_stage is not None and other.origin_stage is not None \
            and self.origin_stage.uid == other.origin_stage.uid

    def traverse(self, acc, f: Callable[[Any, "Feature"], Any]):
        """Fold over the upstream DAG (FeatureLike.scala:316)."""
        acc = f(acc, self)
        for p in self.parents:
            acc = p.traverse(acc, f)
        return acc

    def raw_features(self) -> List["Feature"]:
        """All raw ancestors (FeatureLike.scala:345)."""
        seen: Dict[str, Feature] = {}

        def visit(feat: Feature):
            if feat.uid in seen:
                return
            seen[feat.uid] = feat
            for p in feat.parents:
                visit(p)

        visit(self)
        return sorted((f for f in seen.values() if f.is_raw), key=lambda f: f.name)

    def parent_stages(self) -> Dict["PipelineStage", int]:
        """BFS from this feature: stage -> max distance from result
        (FeatureLike.scala:370).  Distance is the max over all paths — this is
        what makes DAG layers antichains (FitStagesUtil.computeDAG:173)."""
        dist: Dict[str, int] = {}
        stages: Dict[str, "PipelineStage"] = {}
        frontier: List[Tuple[Feature, int]] = [(self, 0)]
        while frontier:
            nxt: List[Tuple[Feature, int]] = []
            for feat, d in frontier:
                st = feat.origin_stage
                if st is not None:
                    if st.uid not in dist or dist[st.uid] < d:
                        dist[st.uid] = d
                        stages[st.uid] = st
                for p in feat.parents:
                    nxt.append((p, d + 1))
            frontier = nxt
        return {stages[uid]: d for uid, d in dist.items()}

    def history(self) -> FeatureHistory:
        """FeatureLike.scala:293 — originating features + stages applied."""
        if self.is_raw:
            return FeatureHistory((self.name,), ())
        h = FeatureHistory((), (self.origin_stage.operation_name,))
        for p in self.parents:
            h = h.merge(p.history())
        return h

    def all_features(self) -> List["Feature"]:
        """Every feature in the upstream closure, this one included."""
        seen: Dict[str, Feature] = {}

        def visit(feat: Feature):
            if feat.uid in seen:
                return
            seen[feat.uid] = feat
            for p in feat.parents:
                visit(p)

        visit(self)
        return list(seen.values())

    def copy_with_new_stages(self, stage_map: Dict[str, "PipelineStage"]) -> "Feature":
        """Rebuild this feature subtree swapping stages by uid
        (FeatureLike.scala:463) — used by workflow-level CV to refit the
        feature DAG per fold on fresh stage copies."""
        new_parents = tuple(p.copy_with_new_stages(stage_map) for p in self.parents)
        new_stage = stage_map.get(self.origin_stage.uid, self.origin_stage)
        return replace(self, parents=new_parents, origin_stage=new_stage)


@dataclass(frozen=True)
class TransientFeature:
    """Serializable feature reference used inside stages — avoids capturing
    the DAG in fitted-model state (reference TransientFeature.scala:61)."""

    name: str
    type_name: str
    is_response: bool
    is_raw: bool
    uid: str

    @staticmethod
    def from_feature(f: Feature) -> "TransientFeature":
        return TransientFeature(f.name, f.ftype.__name__, f.is_response, f.is_raw, f.uid)

    @property
    def ftype(self) -> Type[T.FeatureType]:
        return T.feature_type_by_name(self.type_name)
