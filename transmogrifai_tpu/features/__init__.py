"""Package."""
