"""Vector column metadata — per-column provenance of assembled vectors.

Reference parity: features/.../utils/spark/OpVectorColumnMetadata.scala:67 and
OpVectorMetadata.scala:89.  Every column of every assembled OPVector carries:
``parent_feature_name``, ``parent_feature_type``, ``grouping`` (e.g. the map
key or categorical group), ``indicator_value`` (e.g. the pivoted category),
``descriptor_value`` (e.g. "sin(dayOfWeek)"), and its ``index`` in the vector.

This sidecar powers SanityChecker drop decisions, ModelInsights and
RecordInsightsLOCO — it is a first-class structure here (SURVEY §7).
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

NULL_INDICATOR = "NullIndicatorValue"  # OpVectorColumnMetadata.NullString
OTHER_INDICATOR = "OTHER"              # OpOneHotVectorizer other-category


@dataclass(frozen=True)
class VectorColumnMetadata:
    """One vector slot's provenance (OpVectorColumnMetadata.scala:67)."""

    parent_feature_name: Tuple[str, ...]
    parent_feature_type: Tuple[str, ...]
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    descriptor_value: Optional[str] = None
    index: int = 0

    @property
    def is_null_indicator(self) -> bool:
        """OpVectorColumnMetadata.scala:106."""
        return self.indicator_value == NULL_INDICATOR

    @property
    def is_other_indicator(self) -> bool:
        return self.indicator_value == OTHER_INDICATOR

    def feature_group(self) -> Optional[str]:
        """The categorical-group key for Cramér's-V style stats
        (OpVectorColumnMetadata.scala:158): grouping if set, else the parent
        feature name when this is an indicator column."""
        if self.grouping is not None:
            return f"{self.parent_feature_name[0]}_{self.grouping}" \
                if self.parent_feature_name else self.grouping
        if self.indicator_value is not None and self.parent_feature_name:
            return self.parent_feature_name[0]
        return None

    def make_col_name(self) -> str:
        """OpVectorColumnMetadata.scala:125 makeColName."""
        parent = "_".join(self.parent_feature_name)
        parts = [parent]
        if self.grouping:
            parts.append(self.grouping)
        if self.indicator_value:
            parts.append(self.indicator_value)
        elif self.descriptor_value:
            parts.append(self.descriptor_value)
        parts.append(str(self.index))
        return "_".join(parts)

    def to_json(self) -> Dict[str, Any]:
        return {
            "parentFeatureName": list(self.parent_feature_name),
            "parentFeatureType": list(self.parent_feature_type),
            "grouping": self.grouping,
            "indicatorValue": self.indicator_value,
            "descriptorValue": self.descriptor_value,
            "index": self.index,
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "VectorColumnMetadata":
        return VectorColumnMetadata(
            tuple(d["parentFeatureName"]), tuple(d["parentFeatureType"]),
            d.get("grouping"), d.get("indicatorValue"), d.get("descriptorValue"),
            int(d.get("index", 0)))


@dataclass(frozen=True)
class VectorMetadata:
    """Full vector provenance: ordered columns + per-parent history
    (OpVectorMetadata.scala:89)."""

    name: str
    columns: Tuple[VectorColumnMetadata, ...] = ()

    @property
    def size(self) -> int:
        return len(self.columns)

    def column_names(self) -> List[str]:
        return [c.make_col_name() for c in self.columns]

    def index_of_parent(self, parent_name: str) -> List[int]:
        return [i for i, c in enumerate(self.columns) if parent_name in c.parent_feature_name]

    def select(self, indices: Sequence[int]) -> "VectorMetadata":
        """Slice + reindex (used by SanityChecker's column dropper)."""
        cols = tuple(replace(self.columns[i], index=j) for j, i in enumerate(indices))
        return VectorMetadata(self.name, cols)

    @staticmethod
    def flatten(name: str, parts: Sequence["VectorMetadata"]) -> "VectorMetadata":
        """Concatenate vector metadatas, reindexing (OpVectorMetadata.flatten)."""
        cols: List[VectorColumnMetadata] = []
        for part in parts:
            for c in part.columns:
                cols.append(replace(c, index=len(cols)))
        return VectorMetadata(name, tuple(cols))

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "columns": [c.to_json() for c in self.columns]}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "VectorMetadata":
        return VectorMetadata(d["name"],
                              tuple(VectorColumnMetadata.from_json(c) for c in d["columns"]))


def make_columns(parent_name: str, parent_type: str, *,
                 groupings: Optional[Sequence[Optional[str]]] = None,
                 indicators: Optional[Sequence[Optional[str]]] = None,
                 descriptors: Optional[Sequence[Optional[str]]] = None,
                 n: Optional[int] = None) -> List[VectorColumnMetadata]:
    """Convenience builder for a run of columns sharing one parent feature."""
    if n is None:
        n = max(len(x) for x in (groupings, indicators, descriptors) if x is not None)
    out = []
    for i in range(n):
        out.append(VectorColumnMetadata(
            parent_feature_name=(parent_name,),
            parent_feature_type=(parent_type,),
            grouping=groupings[i] if groupings else None,
            indicator_value=indicators[i] if indicators else None,
            descriptor_value=descriptors[i] if descriptors else None,
            index=i,
        ))
    return out
