"""Monoid aggregators — event aggregation for aggregate/conditional readers.

Reference parity: features/src/main/scala/com/salesforce/op/aggregators/
(algebird ``MonoidAggregator[Event[O], _, O]`` per type; defaults in
MonoidAggregatorDefaults.scala; TimeBasedAggregator first/last-by-time;
CustomMonoidAggregator for user functions).

An aggregator folds a sequence of typed events (value + timestamp) for one
key into a single typed value.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Generic, List, Optional, Sequence, Tuple, Type, TypeVar

from .. import types as T


@dataclass(frozen=True)
class Event:
    """A timestamped value (reference Event[T])."""

    value: T.FeatureType
    time: int = 0


class MonoidAggregator:
    """prepare -> fold(monoid plus) -> present (algebird shape)."""

    name = "monoid"

    def prepare(self, event: Event) -> Any:
        raise NotImplementedError

    def zero(self) -> Any:
        raise NotImplementedError

    def plus(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def present(self, acc: Any, ftype: Type[T.FeatureType]) -> T.FeatureType:
        raise NotImplementedError

    def aggregate(self, ftype: Type[T.FeatureType], events: Sequence[Event]) -> T.FeatureType:
        acc = self.zero()
        for e in events:
            acc = self.plus(acc, self.prepare(e))
        return self.present(acc, ftype)


class _NumericAgg(MonoidAggregator):
    def prepare(self, event: Event) -> Optional[float]:
        v = event.value.value
        return None if v is None else float(v)

    def zero(self):
        return None

    def present(self, acc, ftype):
        if acc is None:
            return T.default_of(ftype)
        if issubclass(ftype, T.Integral):
            return ftype(int(acc))
        return ftype(acc)


class SumNumeric(_NumericAgg):
    name = "Sum"

    def plus(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return a + b


class MaxNumeric(_NumericAgg):
    name = "Max"

    def plus(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return max(a, b)


class MinNumeric(_NumericAgg):
    name = "Min"

    def plus(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return min(a, b)


class MeanNumeric(MonoidAggregator):
    name = "Mean"

    def prepare(self, event):
        v = event.value.value
        return (0.0, 0) if v is None else (float(v), 1)

    def zero(self):
        return (0.0, 0)

    def plus(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def present(self, acc, ftype):
        s, n = acc
        return T.default_of(ftype) if n == 0 else ftype(s / n)


class LogicalOr(_NumericAgg):
    name = "LogicalOr"

    def plus(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return bool(a) or bool(b)


class ConcatText(MonoidAggregator):
    """Concatenate non-empty texts with a separator (reference ConcatTextWithSeparator)."""

    name = "ConcatText"

    def __init__(self, separator: str = " "):
        self.separator = separator

    def prepare(self, event):
        v = event.value.value
        return [] if v is None else [str(v)]

    def zero(self):
        return []

    def plus(self, a, b):
        return a + b

    def present(self, acc, ftype):
        return ftype(self.separator.join(acc)) if acc else ftype(None)


class UnionCollection(MonoidAggregator):
    """Union of lists/sets (reference UnionTextList / UnionMultiPickList)."""

    name = "Union"

    def prepare(self, event):
        v = event.value.value
        return list(v) if v else []

    def zero(self):
        return []

    def plus(self, a, b):
        return a + b

    def present(self, acc, ftype):
        return ftype(acc if acc else None)


class UnionMap(MonoidAggregator):
    """Right-biased map merge (reference UnionMaps family)."""

    name = "UnionMap"

    def prepare(self, event):
        v = event.value.value
        return dict(v) if v else {}

    def zero(self):
        return {}

    def plus(self, a, b):
        out = dict(a)
        out.update(b)
        return out

    def present(self, acc, ftype):
        return ftype(acc if acc else None)


class TimeBasedAggregator(MonoidAggregator):
    """Keep first/last non-empty value by event time
    (aggregators/TimeBasedAggregator.scala)."""

    def __init__(self, last: bool = True):
        self.last = last
        self.name = "LastByTime" if last else "FirstByTime"

    def prepare(self, event):
        if event.value.is_empty:
            return None
        return (event.time, event.value)

    def zero(self):
        return None

    def plus(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if self.last:
            return b if b[0] >= a[0] else a
        return a if a[0] <= b[0] else b

    def present(self, acc, ftype):
        return T.default_of(ftype) if acc is None else acc[1]


class CustomMonoidAggregator(MonoidAggregator):
    """User-supplied zero/plus over raw values (CustomMonoidAggregator)."""

    name = "Custom"

    def __init__(self, zero_value: Any, plus_fn: Callable[[Any, Any], Any]):
        self._zero = zero_value
        self._plus = plus_fn

    def prepare(self, event):
        return event.value.value

    def zero(self):
        return self._zero

    def plus(self, a, b):
        if b is None:
            return a
        return self._plus(a, b)

    def present(self, acc, ftype):
        return ftype(acc)


def default_aggregator(ftype: Type[T.FeatureType]) -> MonoidAggregator:
    """Per-type defaults (MonoidAggregatorDefaults.scala)."""
    if issubclass(ftype, T.Binary):
        return LogicalOr()
    if issubclass(ftype, (T.Date, T.DateTime)):
        return MaxNumeric()
    if issubclass(ftype, T.Percent):
        return MeanNumeric()
    if issubclass(ftype, T.OPNumeric):
        return SumNumeric()
    if issubclass(ftype, T.OPMap):
        return UnionMap()
    if issubclass(ftype, (T.OPList, T.OPSet)):
        return UnionCollection()
    if issubclass(ftype, (T.PickList, T.ComboBox, T.ID, T.Country, T.State,
                          T.City, T.PostalCode, T.Street)):
        return TimeBasedAggregator(last=True)
    if issubclass(ftype, T.Text):
        return ConcatText()
    return TimeBasedAggregator(last=True)
