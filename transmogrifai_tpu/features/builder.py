"""FeatureBuilder — the user entry point for defining raw features.

Reference parity: features/src/main/scala/com/salesforce/op/features/FeatureBuilder.scala:48 —
``FeatureBuilder.Text[Passenger].extract(...).asPredictor`` and
``FeatureBuilder.fromDataFrame[RealNN](df, response=...)`` which auto-infers
features from a schema (:232).

Python surface::

    age  = FeatureBuilder.real("age").extract(field="age").as_predictor()
    name = FeatureBuilder.text("name").extract(lambda r: r["name"]).as_predictor()
    feats, label = FeatureBuilder.from_dataframe(df, response="survived")
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Type, Union

import numpy as np

from .. import types as T
from .aggregators import MonoidAggregator
from .feature import Feature
from .generator import Extractor, FieldExtractor, FnExtractor, FeatureGeneratorStage


class FeatureBuilderWithExtract:
    """Second step: extractor attached, choose predictor/response + aggregation
    (reference FeatureBuilderWithExtract, FeatureBuilder.scala:297)."""

    def __init__(self, name: str, ftype: Type[T.FeatureType], extractor: Extractor):
        self.name = name
        self.ftype = ftype
        self.extractor = extractor
        self._aggregator: Optional[MonoidAggregator] = None
        self._window_ms: Optional[int] = None

    def aggregate(self, aggregator: MonoidAggregator) -> "FeatureBuilderWithExtract":
        self._aggregator = aggregator
        return self

    def window(self, window_ms: int) -> "FeatureBuilderWithExtract":
        self._window_ms = window_ms
        return self

    def _build(self, is_response: bool) -> Feature:
        stage = FeatureGeneratorStage(
            extract_fn=self.extractor, output_type=self.ftype, output_name=self.name,
            is_response=is_response, aggregator=self._aggregator,
            aggregate_window_ms=self._window_ms)
        return stage.get_output()

    def as_predictor(self) -> Feature:
        return self._build(is_response=False)

    def as_response(self) -> Feature:
        return self._build(is_response=True)


class FeatureBuilder:
    """First step: named + typed; ``extract`` attaches the extract function."""

    def __init__(self, name: str, ftype: Type[T.FeatureType]):
        self.name = name
        self.ftype = ftype

    def extract(self, fn: Optional[Callable[[Any], Any]] = None, *,
                field: Optional[str] = None) -> FeatureBuilderWithExtract:
        if (fn is None) == (field is None):
            raise ValueError("extract() takes exactly one of fn= or field=")
        extractor: Extractor
        if field is not None:
            extractor = FieldExtractor(field, self.ftype)
        else:
            extractor = FnExtractor(fn, self.ftype)
        return FeatureBuilderWithExtract(self.name, self.ftype, extractor)

    def from_field(self) -> FeatureBuilderWithExtract:
        """Extract the record field with the same name as the feature."""
        return self.extract(field=self.name)

    # ---- typed constructors (FeatureBuilder.Text / .Real / ... analog) -----
    @classmethod
    def _typed(cls, ftype: Type[T.FeatureType]):
        def ctor(name: str) -> "FeatureBuilder":
            return cls(name, ftype)
        return ctor


# install FeatureBuilder.real / .text / ... for every concrete type
for _name, _t in T.FEATURE_TYPES.items():
    _snake = "".join(("_" + c.lower() if c.isupper() and i else c.lower())
                     for i, c in enumerate(_name))
    setattr(FeatureBuilder, _snake, staticmethod(FeatureBuilder._typed(_t)))
    setattr(FeatureBuilder, _name, staticmethod(FeatureBuilder._typed(_t)))


def _infer_ftype(dtype, series=None) -> Type[T.FeatureType]:
    """Schema inference for from_dataframe (FeatureBuilder.scala:232
    fromDataFrame maps Spark SQL types to feature types)."""
    import pandas as pd

    if pd.api.types.is_bool_dtype(dtype):
        return T.Binary
    if pd.api.types.is_integer_dtype(dtype):
        return T.Integral
    if pd.api.types.is_float_dtype(dtype):
        return T.Real
    if pd.api.types.is_datetime64_any_dtype(dtype):
        return T.DateTime
    return T.Text


def from_dataframe(df, response: str,
                   response_type: Type[T.FeatureType] = T.RealNN,
                   feature_types: Optional[Dict[str, Type[T.FeatureType]]] = None,
                   ignore: Tuple[str, ...] = (),
                   ) -> Tuple[List[Feature], Feature]:
    """Auto-infer raw features from a pandas DataFrame schema.

    Returns (predictor features, response feature).  Reference parity:
    ``FeatureBuilder.fromDataFrame`` (FeatureBuilder.scala:232).
    """
    if response not in df.columns:
        raise ValueError(
            f"Response feature {response!r} is not present in the dataframe: {list(df.columns)}")
    feature_types = feature_types or {}
    label = FeatureBuilder(response, response_type).extract(field=response).as_response()
    feats: List[Feature] = []
    for col in df.columns:
        if col == response or col in ignore:
            continue
        ftype = feature_types.get(col) or _infer_ftype(df[col].dtype, df[col])
        feats.append(FeatureBuilder(col, ftype).extract(field=col).as_predictor())
    return feats, label


FeatureBuilder.from_dataframe = staticmethod(from_dataframe)
