"""FeatureGeneratorStage — the DAG origin stage for raw features.

Reference parity: features/.../stages/FeatureGeneratorStage.scala:67 — holds
the extract function, a MonoidAggregator and an optional time window for
event aggregation (GenericFeatureAggregator, aggregators/FeatureAggregator.scala:100).

Serialization note (SURVEY §7 "Hard parts"): the reference serializes extract
closures by source string; we use *declarative extractor specs* instead —
a named-field extractor is fully serializable, arbitrary callables are
supported in-session and flagged at save time.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Type

from .. import types as T
from ..stages.base import PipelineStage
from .aggregators import Event, MonoidAggregator, default_aggregator


class Extractor:
    """Declarative extract function: record -> FeatureType."""

    spec: Dict[str, Any]

    def __call__(self, record: Any) -> T.FeatureType:
        raise NotImplementedError


@dataclass
class FieldExtractor(Extractor):
    """Extract a named field from a mapping/attribute record — serializable."""

    field_name: str
    ftype: Type[T.FeatureType]

    def __call__(self, record: Any) -> T.FeatureType:
        if isinstance(record, dict):
            raw = record.get(self.field_name)
        else:
            raw = getattr(record, self.field_name, None)
        if isinstance(raw, float) and raw != raw:  # NaN -> missing
            raw = None
        if raw is None:
            # Missing field: fall back to the type default so scoring data
            # without e.g. the label column still flows (the reference scores
            # unlabeled data the same way — nullable-everywhere semantics;
            # RealNN default is 0.0 and evaluators mask unlabeled rows).
            return T.default_of(self.ftype)
        return T.make(self.ftype, raw)

    @property
    def spec(self) -> Dict[str, Any]:
        return {"kind": "field", "field": self.field_name, "type": self.ftype.__name__}


@dataclass
class FnExtractor(Extractor):
    """Arbitrary callable extractor — not serializable across processes."""

    fn: Callable[[Any], Any]
    ftype: Type[T.FeatureType]

    def __call__(self, record: Any) -> T.FeatureType:
        out = self.fn(record)
        if isinstance(out, T.FeatureType):
            return out
        return T.make(self.ftype, out)

    @property
    def spec(self) -> Dict[str, Any]:
        return {"kind": "fn", "type": self.ftype.__name__,
                "repr": getattr(self.fn, "__name__", repr(self.fn))}


def extractor_from_spec(spec: Dict[str, Any]) -> Extractor:
    if spec.get("kind") == "field":
        return FieldExtractor(spec["field"], T.feature_type_by_name(spec["type"]))
    raise ValueError(f"Cannot reconstruct extractor from spec: {spec!r}")


class FeatureGeneratorStage(PipelineStage):
    """Origin stage of a raw feature (FeatureGeneratorStage.scala:67)."""

    def __init__(self, extract_fn: Extractor, output_type: Type[T.FeatureType],
                 output_name: str, is_response: bool = False,
                 aggregator: Optional[MonoidAggregator] = None,
                 aggregate_window_ms: Optional[int] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name=f"FeatureGeneratorStage_{output_name}",
                         output_type=output_type, uid=uid)
        self.extract_fn = extract_fn
        self._output_name = output_name
        self.is_response = is_response
        self.aggregator = aggregator or default_aggregator(output_type)
        self.aggregate_window_ms = aggregate_window_ms

    def output_name(self, index: int = 0) -> str:
        return self._output_name

    def output_is_response(self) -> bool:
        return self.is_response

    def extract(self, record: Any) -> T.FeatureType:
        return self.extract_fn(record)

    def aggregate(self, events: Sequence[Event], cutoff_ms: Optional[int] = None,
                  responses_after_cutoff: bool = False,
                  response_window_inclusive: bool = True) -> T.FeatureType:
        """GenericFeatureAggregator semantics (FeatureAggregator.scala:100):
        predictors aggregate events strictly *before* the cutoff, responses
        events *at/after* it; the optional window further restricts the range.

        ``response_window_inclusive``: the plain aggregate path bounds the
        response window INCLUSIVELY (date <= cutoff + window,
        FeatureAggregator.scala:121) but the post-join aggregation uses an
        EXCLUSIVE bound (timeStamp < cutOff + timeWindow,
        JoinedDataReader.scala:434) — JoinedAggregateReader passes False.
        """
        sel = events
        if cutoff_ms is not None:
            if responses_after_cutoff:
                sel = [e for e in events if e.time >= cutoff_ms]
                if self.aggregate_window_ms is not None:
                    hi = cutoff_ms + self.aggregate_window_ms
                    sel = [e for e in sel
                           if (e.time <= hi if response_window_inclusive
                               else e.time < hi)]
            else:
                sel = [e for e in events if e.time < cutoff_ms]
                if self.aggregate_window_ms is not None:
                    sel = [e for e in sel if e.time >= cutoff_ms - self.aggregate_window_ms]
        return self.aggregator.aggregate(self.output_type, sel)
