"""Contract-spec assertions — the OpTransformerSpec / OpEstimatorSpec analog.

The reference's most distinctive testing idea (SURVEY §4): every stage test
asserts the same uniform contract.  Here:

- batch ``transform_columns`` ≡ row-wise ``transform_row`` on every row,
- stage serialization round-trip (encode -> decode -> same outputs),
- fitted-model identity (uid/inputs/outputs preserved through ``fit``),
- feature lineage sanity (``assert_feature``).
"""
from __future__ import annotations

from typing import Optional, Sequence, Type

import numpy as np

from .. import types as T
from ..columns import (Column, Dataset, NumericColumn, ObjectColumn,
                       PredictionColumn, VectorColumn)
from ..features.feature import Feature
from ..stages.base import Estimator, Model, PipelineStage, Transformer


def _scalar_eq(a: T.FeatureType, b: T.FeatureType) -> bool:
    va, vb = a.value, b.value
    if isinstance(va, float) and isinstance(vb, float):
        return (np.isnan(va) and np.isnan(vb)) or abs(va - vb) < 1e-5
    if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
        return np.allclose(np.asarray(va, dtype=float), np.asarray(vb, dtype=float),
                           atol=1e-5)
    return va == vb


def _columns_close(a: Column, b: Column) -> bool:
    if isinstance(a, NumericColumn) and isinstance(b, NumericColumn):
        return (np.array_equal(a.mask, b.mask)
                and np.allclose(a.values[a.mask], b.values[b.mask], atol=1e-5))
    if isinstance(a, VectorColumn) and isinstance(b, VectorColumn):
        return a.values.shape == b.values.shape and np.allclose(a.values, b.values,
                                                                atol=1e-5)
    if isinstance(a, PredictionColumn) and isinstance(b, PredictionColumn):
        return np.allclose(a.prediction, b.prediction, atol=1e-5)
    return all(_scalar_eq(a.to_scalar(i), b.to_scalar(i)) for i in range(len(a)))


def assert_batch_row_parity(stage: Transformer, ds: Dataset,
                            check_rows: Optional[int] = 10) -> None:
    """Batch transform ≡ row-wise transform (OpTransformerSpec's core check)."""
    batch = stage.transform_dataset(ds)
    n = len(batch) if check_rows is None else min(check_rows, len(batch))
    for i in range(n):
        row = {f.name: ds[f.name].to_scalar(i) for f in stage.inputs}
        row_out = stage.transform_row(row)
        batch_out = batch.to_scalar(i)
        assert _scalar_eq(batch_out, row_out), (
            f"batch≠row at {i}: batch={batch_out.value!r} row={row_out.value!r} "
            f"for stage {stage}")


def assert_serialization_roundtrip(stage: PipelineStage, ds: Dataset) -> None:
    """encode -> decode -> identical transform output."""
    from ..workflow.serialization import _decode_stage, _encode_stage

    arrays: dict = {}
    encoded = _encode_stage(stage, arrays)
    restored = _decode_stage(encoded, arrays)
    restored.inputs = stage.inputs
    restored._outputs = stage._outputs
    assert restored.uid == stage.uid
    assert type(restored) is type(stage)
    if isinstance(stage, Transformer):
        a = stage.transform_dataset(ds)
        b = restored.transform_dataset(ds)
        assert _columns_close(a, b), f"serialization changed outputs of {stage}"


def assert_transformer_contract(stage: Transformer, ds: Dataset,
                                expected: Optional[Sequence] = None,
                                check_rows: Optional[int] = 10) -> Column:
    """The OpTransformerSpec bundle: output values (optional), batch≡row,
    serialization round-trip.  Returns the batch output column."""
    out = stage.transform_dataset(ds)
    assert len(out) == len(ds), "output row count must match input"
    if expected is not None:
        for i, e in enumerate(expected):
            got = out.to_scalar(i)
            want = e if isinstance(e, T.FeatureType) else T.make(stage.output_type, e)
            assert _scalar_eq(got, want), f"row {i}: got {got.value!r} want {want.value!r}"
    assert_batch_row_parity(stage, ds, check_rows)
    assert_serialization_roundtrip(stage, ds)
    return out


def assert_estimator_contract(stage: Estimator, ds: Dataset,
                              expected: Optional[Sequence] = None,
                              check_rows: Optional[int] = 10) -> Column:
    """The OpEstimatorSpec bundle: fit -> model identity + transformer contract."""
    model = stage.fit(ds)
    assert isinstance(model, Model), f"fit must return a Model, got {type(model)}"
    assert model.uid == stage.uid, "fitted model must keep the estimator uid"
    assert model.inputs == stage.inputs
    return assert_transformer_contract(model, ds, expected, check_rows)


def assert_feature(f: Feature, name: Optional[str] = None,
                   ftype: Optional[Type[T.FeatureType]] = None,
                   is_response: Optional[bool] = None,
                   origin_ops: Optional[Sequence[str]] = None) -> None:
    """FeatureAsserts.assertFeature (testkit/.../test/FeatureAsserts.scala:63)."""
    assert f.uid, "feature must have a uid"
    if name is not None:
        assert f.name == name, f"name {f.name!r} != {name!r}"
    if ftype is not None:
        assert f.ftype is ftype, f"type {f.ftype} != {ftype}"
    if is_response is not None:
        assert f.is_response == is_response
    if origin_ops is not None:
        hist = f.history()
        assert set(origin_ops) <= set(hist.stages), \
            f"history {hist.stages} missing {origin_ops}"
