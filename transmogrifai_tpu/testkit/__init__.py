"""testkit — random typed-data generators + test fixtures + contract specs.

Reference parity (testkit/src/main/scala/com/salesforce/op/{testkit,test}/):
- random generators for every FeatureType with null-probability control
  (``RandomReal:45``, ``RandomText:49``, ``RandomList/Map/Set/Binary/
  Integral/Vector``; distributions normal/poisson/uniform),
- ``TestFeatureBuilder:50`` — build (Dataset, Feature handles) from inline
  values,
- ``FeatureAsserts.assertFeature:63`` + the ``OpTransformerSpec`` /
  ``OpEstimatorSpec`` contract checks (features/.../test/OpTransformerSpec.scala:53):
  batch ``transform`` ≡ row-wise ``transform_row``, serialization
  round-trip, output metadata sanity.
"""
from .random_data import (RandomBinary, RandomData, RandomDate, RandomDateList,
                          RandomGeolocation, RandomIntegral, RandomList, RandomMap,
                          RandomMultiPickList, RandomReal, RandomText, RandomVector)
from .builder import TestFeatureBuilder
from .asserts import (assert_estimator_contract, assert_feature,
                      assert_transformer_contract)

__all__ = [n for n in dir() if not n.startswith("_")]
