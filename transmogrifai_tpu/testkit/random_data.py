"""Random typed-data generators (testkit/.../testkit/Random*.scala).

Every generator produces FeatureType instances with a controllable
``prob_null``; ``take(n)`` is deterministic given the generator's seed.
"""
from __future__ import annotations

import string
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import types as T


class RandomData:
    """Base generator (RandomData.scala:44)."""

    def __init__(self, ftype, prob_null: float = 0.0, seed: int = 42):
        self.ftype = ftype
        self.prob_null = float(prob_null)
        self.seed = int(seed)

    def with_prob_null(self, p: float) -> "RandomData":
        self.prob_null = float(p)
        return self

    def _value(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError

    def take(self, n: int) -> List[T.FeatureType]:
        rng = np.random.default_rng(self.seed)
        out = []
        for _ in range(n):
            if self.prob_null > 0 and rng.random() < self.prob_null:
                out.append(T.default_of(self.ftype))
            else:
                out.append(T.make(self.ftype, self._value(rng)))
        return out

    def limit(self, n: int) -> List[T.FeatureType]:  # reference API alias
        return self.take(n)


class RandomReal(RandomData):
    """Normal / uniform / poisson reals (RandomReal.scala:45)."""

    def __init__(self, ftype=T.Real, distribution: str = "normal",
                 mean: float = 0.0, sigma: float = 1.0, low: float = 0.0,
                 high: float = 1.0, lam: float = 1.0, prob_null: float = 0.0,
                 seed: int = 42):
        super().__init__(ftype, prob_null, seed)
        assert distribution in ("normal", "uniform", "poisson")
        self.distribution = distribution
        self.mean, self.sigma, self.low, self.high, self.lam = mean, sigma, low, high, lam

    @classmethod
    def normal(cls, mean: float = 0.0, sigma: float = 1.0, **kw) -> "RandomReal":
        return cls(distribution="normal", mean=mean, sigma=sigma, **kw)

    @classmethod
    def uniform(cls, low: float = 0.0, high: float = 1.0, **kw) -> "RandomReal":
        return cls(distribution="uniform", low=low, high=high, **kw)

    @classmethod
    def poisson(cls, lam: float = 1.0, **kw) -> "RandomReal":
        return cls(distribution="poisson", lam=lam, **kw)

    def _value(self, rng):
        if self.distribution == "normal":
            return float(rng.normal(self.mean, self.sigma))
        if self.distribution == "uniform":
            return float(rng.uniform(self.low, self.high))
        return float(rng.poisson(self.lam))


class RandomIntegral(RandomData):
    def __init__(self, low: int = 0, high: int = 100, prob_null: float = 0.0,
                 seed: int = 42, ftype=T.Integral):
        super().__init__(ftype, prob_null, seed)
        self.low, self.high = int(low), int(high)

    def _value(self, rng):
        return int(rng.integers(self.low, self.high))


class RandomBinary(RandomData):
    def __init__(self, prob_true: float = 0.5, prob_null: float = 0.0, seed: int = 42):
        super().__init__(T.Binary, prob_null, seed)
        self.prob_true = float(prob_true)

    def _value(self, rng):
        return bool(rng.random() < self.prob_true)


class RandomDate(RandomIntegral):
    """Epoch-millis dates in a range (RandomIntegral over time)."""

    def __init__(self, start_ms: int = 0, end_ms: int = 1_600_000_000_000,
                 prob_null: float = 0.0, seed: int = 42):
        super().__init__(start_ms, end_ms, prob_null, seed, ftype=T.Date)


class RandomText(RandomData):
    """Random words / picklist domains / emails / urls (RandomText.scala:49)."""

    def __init__(self, ftype=T.Text, domain: Optional[Sequence[str]] = None,
                 n_words: int = 3, word_len: int = 6, prob_null: float = 0.0,
                 seed: int = 42):
        super().__init__(ftype, prob_null, seed)
        self.domain = list(domain) if domain is not None else None
        self.n_words, self.word_len = n_words, word_len

    @classmethod
    def of(cls, domain: Sequence[str], ftype=T.PickList, **kw) -> "RandomText":
        return cls(ftype=ftype, domain=domain, **kw)

    @classmethod
    def emails(cls, host: str = "example.com", **kw) -> "RandomText":
        gen = cls(ftype=T.Email, **kw)
        gen._email_host = host
        return gen

    def _word(self, rng) -> str:
        letters = rng.integers(0, 26, self.word_len)
        return "".join(string.ascii_lowercase[i] for i in letters)

    def _value(self, rng):
        if getattr(self, "_email_host", None):
            return f"{self._word(rng)}@{self._email_host}"
        if self.domain is not None:
            return self.domain[int(rng.integers(0, len(self.domain)))]
        return " ".join(self._word(rng) for _ in range(self.n_words))


class RandomList(RandomData):
    def __init__(self, element: RandomData, min_len: int = 0, max_len: int = 5,
                 ftype=T.TextList, prob_null: float = 0.0, seed: int = 42):
        super().__init__(ftype, prob_null, seed)
        self.element = element
        self.min_len, self.max_len = min_len, max_len

    def _value(self, rng):
        k = int(rng.integers(self.min_len, self.max_len + 1))
        return [self.element._value(rng) for _ in range(k)]


class RandomDateList(RandomList):
    def __init__(self, start_ms: int = 0, end_ms: int = 1_600_000_000_000,
                 min_len: int = 0, max_len: int = 5, prob_null: float = 0.0,
                 seed: int = 42):
        super().__init__(RandomDate(start_ms, end_ms), min_len, max_len,
                         ftype=T.DateList, prob_null=prob_null, seed=seed)


class RandomMultiPickList(RandomData):
    def __init__(self, domain: Sequence[str], min_len: int = 0, max_len: int = 3,
                 prob_null: float = 0.0, seed: int = 42):
        super().__init__(T.MultiPickList, prob_null, seed)
        self.domain = list(domain)
        self.min_len, self.max_len = min_len, max_len

    def _value(self, rng):
        k = int(rng.integers(self.min_len, min(self.max_len, len(self.domain)) + 1))
        return set(rng.choice(self.domain, size=k, replace=False).tolist())


class RandomMap(RandomData):
    def __init__(self, value_gen: RandomData, keys: Sequence[str],
                 ftype=T.TextMap, prob_missing_key: float = 0.2,
                 prob_null: float = 0.0, seed: int = 42):
        super().__init__(ftype, prob_null, seed)
        self.value_gen = value_gen
        self.keys = list(keys)
        self.prob_missing_key = float(prob_missing_key)

    def _value(self, rng):
        return {k: self.value_gen._value(rng) for k in self.keys
                if rng.random() >= self.prob_missing_key}


class RandomGeolocation(RandomData):
    def __init__(self, prob_null: float = 0.0, seed: int = 42):
        super().__init__(T.Geolocation, prob_null, seed)

    def _value(self, rng):
        return [float(rng.uniform(-90, 90)), float(rng.uniform(-180, 180)),
                float(rng.integers(1, 10))]


class RandomVector(RandomData):
    def __init__(self, dim: int = 8, prob_null: float = 0.0, seed: int = 42):
        super().__init__(T.OPVector, prob_null, seed)
        self.dim = int(dim)

    def _value(self, rng):
        return rng.standard_normal(self.dim).astype(np.float32)
