"""TestFeatureBuilder — (Dataset, Feature handles) from inline values
(testkit/.../test/TestFeatureBuilder.scala:50)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from .. import types as T
from ..columns import Dataset, column_from_scalars
from ..features.builder import FeatureBuilder
from ..features.feature import Feature


class TestFeatureBuilder:
    """Build a Dataset plus raw Feature handles from inline columns.

    >>> ds, (x, label) = TestFeatureBuilder.of(
    ...     ("x", T.Real, [1.0, None, 3.0]),
    ...     ("label", T.RealNN, [0.0, 1.0, 0.0]), response="label")
    """

    @staticmethod
    def of(*columns: Tuple[str, Type[T.FeatureType], Sequence[Any]],
           response: Optional[str] = None,
           key: Optional[Sequence[str]] = None) -> Tuple[Dataset, List[Feature]]:
        if not columns:
            raise ValueError("At least one column is required")
        n = len(columns[0][2])
        cols: Dict[str, Any] = {}
        feats: List[Feature] = []
        for name, ftype, values in columns:
            if len(values) != n:
                raise ValueError(f"Column {name!r} has {len(values)} rows, expected {n}")
            scalars = [v if isinstance(v, T.FeatureType) else T.make(ftype, v)
                       for v in values]
            cols[name] = column_from_scalars(ftype, scalars)
            fb = FeatureBuilder(name, ftype).from_field()
            feats.append(fb.as_response() if name == response else fb.as_predictor())
        keys = np.array([str(k) for k in (key if key is not None else range(n))],
                        dtype=object)
        return Dataset(cols, keys), feats

    @staticmethod
    def random(n: int, *gens: Tuple[str, "object"],
               response: Optional[str] = None) -> Tuple[Dataset, List[Feature]]:
        """Build from (name, RandomData generator) pairs."""
        cols = [(name, gen.ftype, gen.take(n)) for name, gen in gens]
        return TestFeatureBuilder.of(*cols, response=response)
