"""Measured CPU baseline for the Titanic default sweep (VERDICT r3 #4).

The reference publishes no wall-clock numbers and Spark is not installed in
this image, so the closest HONEST proxy is measured here: the same 28-grid x
3-fold sweep shape (LR 8 + RF 18 + boosted 2 — reference defaults,
BinaryClassificationModelSelector.scala:81-135) on the SAME vectorized
Titanic matrix this framework trains on, fitted with scikit-learn — the
standard, heavily-optimized C/Cython CPU implementations of exactly the
model families Spark MLlib wraps (netlib BLAS LR, CART forests, gradient
boosting).

This container exposes ONE CPU core (os.cpu_count() == 1; round-3 notes
assumed 32).  The reference sweep runs 8 JVM threads
(ValidatorParamDefaults.Parallelism=8, OpValidator.scala:373-380), so the
recorded baseline is the single-core measurement times a PERFECT 8x linear
scaling — generous to the baseline (real Spark pays scheduler/JVM overhead
and never scales linearly), hence conservative for any speedup quoted
against it.

Writes BASELINE_MEASURED.json; bench.py uses it as the ``vs_baseline``
denominator.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

THREADS_EXTRAPOLATED = 8


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # vectorize on CPU only
    from sklearn.ensemble import (HistGradientBoostingClassifier,
                                  RandomForestClassifier)
    from sklearn.linear_model import LogisticRegression
    from sklearn.model_selection import StratifiedKFold

    from bench import titanic_arrays

    X, y = titanic_arrays()
    n = len(y)

    # the reference default grids (DefaultSelectorParams.scala:37-75)
    lr_grids = [dict(C=1.0 / (reg * n), l1_ratio=en)
                for reg in (0.001, 0.01, 0.1, 0.2) for en in (0.1, 0.5)]
    rf_grids = [dict(max_depth=md, min_impurity_decrease=mig,
                     min_samples_leaf=mspn)
                for md in (3, 6, 12) for mig in (0.001, 0.01, 0.1)
                for mspn in (10, 100)]
    xgb_grids = [dict(max_depth=10, max_iter=200, learning_rate=0.02,
                      min_samples_leaf=int(mcw)) for mcw in (1, 10)]

    skf = StratifiedKFold(n_splits=3, shuffle=True, random_state=42)
    folds = list(skf.split(X, y))

    t0 = time.perf_counter()
    fits = 0
    for grids, make in (
        (lr_grids, lambda g: LogisticRegression(
            penalty="elasticnet", solver="saga", max_iter=50, **g)),
        (rf_grids, lambda g: RandomForestClassifier(
            n_estimators=50, max_features="sqrt", n_jobs=1, **g)),
        (xgb_grids, lambda g: HistGradientBoostingClassifier(
            max_bins=32, early_stopping=False, **g)),
    ):
        for g in grids:
            for tr, va in folds:
                clf = make(g)
                clf.fit(X[tr], y[tr])
                clf.predict_proba(X[va])
                fits += 1
    dt = time.perf_counter() - t0

    out = {
        "metric": "baseline_sklearn_sweep_models_per_sec",
        "models": fits,
        "wall_clock_s": round(dt, 2),
        "models_per_sec_1core": round(fits / dt, 3),
        "threads_extrapolated": THREADS_EXTRAPOLATED,
        "models_per_sec_8thread_linear": round(fits / dt * THREADS_EXTRAPOLATED, 3),
        "note": "sklearn LR(saga elasticnet)+RF(50 trees)+HistGB(200 rounds "
                "d10) on the framework's own vectorized Titanic matrix; "
                "single measured core x perfect 8x scaling (generous to the "
                "baseline; reference sweep uses 8 JVM threads)",
        "cpu_count": os.cpu_count(),
    }
    print(json.dumps(out))
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BASELINE_MEASURED.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
