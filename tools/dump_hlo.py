import numpy as np
from bench import init_backend
init_backend()
import jax, jax.numpy as jnp
from transmogrifai_tpu.ops import trees as Tr

n, d = 891, 24
rng = np.random.default_rng(0)
X = rng.normal(size=(n, d)).astype(np.float32)
y = (rng.random(n) < 0.4).astype(np.float32)
Xb, _ = Tr.quantize(X, 32)
G = -y[:, None]; H = np.ones(n, np.float32)
TT = 900
wt = rng.poisson(1.0, size=(TT, n)).astype(np.float32)
fm = (rng.random((TT, d)) < 0.3).astype(np.float32)
mcw = np.full(TT, 10.0, np.float32)
a = [jnp.asarray(v) for v in (Xb, G, H, wt, fm, mcw)]
lowered = jax.jit(lambda *a: Tr.fit_forest_chunked(*a, max_depth=12, n_bins=32,
                                                   chunk=TT, frontier=128)).lower(*a)
comp = lowered.compile()
txt = comp.as_text()
open("/tmp/hlo.txt", "w").write(txt)
print(len(txt))
