"""Per-fragment device-time profile of the fused sweep (dev tool)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import init_backend, titanic_arrays, make_selector

platform, fb = init_backend()
print("platform:", platform)

from transmogrifai_tpu.impl.sweep_fragments import build_sweep_plan
from transmogrifai_tpu.ops.sweep import run_sweep

X, y = titanic_arrays()
sel = make_selector()
v = sel.validator
n = len(y)
train_w, val_mask = v.make_folds(n, None)
prep_w = sel.splitter.prepare_weights(y)
train_w = train_w * prep_w[None, :].astype(np.float32)
val_mask = val_mask & (prep_w > 0)[None, :]

plan = build_sweep_plan(sel.models, X, y, train_w, v.evaluator)
full = plan.spec


def time_spec(name, frags, strict_len):
    spec = (full[0], frags, full[2][:strict_len])
    # remap cis to 0..strict_len-1? metrics tensor sized by strict tuple —
    # keep global C; scores for absent candidates stay zero, harmless
    spec = (full[0], frags, full[2])
    t0 = time.perf_counter()
    m = run_sweep(spec, plan.X, plan.xbs, plan.y, train_w, val_mask, plan.blob)
    np.asarray(m)
    warm = time.perf_counter() - t0
    reps = 5
    t0 = time.perf_counter()
    for r in range(reps):
        tw = train_w * (1.0 + 1e-7 * r)  # new buffer: defeat memoization
        m = run_sweep(spec, plan.X, plan.xbs, plan.y, tw, val_mask, plan.blob)
        np.asarray(m)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:24s} warm={warm:7.2f}s steady={dt*1e3:9.1f} ms")
    return dt


frags = full[1]
by_kind = {}
for f in frags:
    by_kind.setdefault(f[0], []).append(f)

time_spec("ALL", frags, len(full[2]))
for kind, fs in by_kind.items():
    time_spec(f"only:{kind}", tuple(fs), len(full[2]))
if "forest" in by_kind:
    groups = by_kind["forest"][0][2]
    for g in groups:
        frag = ("forest", by_kind["forest"][0][1], (g,))
        time_spec(f"forest depth={g[1]} frontier={g[9]} chunk={g[11]}", (frag,),
                  len(full[2]))
