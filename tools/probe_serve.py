"""Loopback load generator for the serve/ subsystem (BENCH rounds).

Trains a small testkit model in-process (or loads --model-location), starts
a ModelServer on an ephemeral port, and hammers it with N client threads for
a fixed duration.  Prints one JSON line: throughput, client-side
p50/p95/p99 latency, replica count, per-replica QPS/p99, compile-cache
hit/miss counters, and the server's own /metrics snapshot (batch occupancy,
shed/fallback counters) — comparable across rounds.  The same payload is
appended as a schema-versioned JSONL run record via ``obs/record.py``
(TMOG_TELEMETRY or ./telemetry.jsonl), so serve runs feed the costmodel
telemetry like bench/profile runs do.

    python tools/probe_serve.py --concurrency 64 --duration 10
    python tools/probe_serve.py --replicas 8 --compile-cache /tmp/aotx
    python tools/probe_serve.py --model-location /tmp/m --record '{"x": 1.0}'
    python tools/probe_serve.py --replicas 2 --kill-replica 0 --duration 8
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _train_demo_model():
    """Tiny logistic model over (real, picklist) testkit features."""
    import numpy as np

    import transmogrifai_tpu.types as T
    from transmogrifai_tpu import OpWorkflow
    from transmogrifai_tpu.impl.classification.logistic import OpLogisticRegression
    from transmogrifai_tpu.impl.feature.vectorizers import (
        OneHotVectorizer, RealVectorizer, VectorsCombiner)
    from transmogrifai_tpu.testkit import TestFeatureBuilder

    n = 256
    ds, (x, cat, y) = TestFeatureBuilder.of(
        ("x", T.Real, list(np.linspace(-2, 2, n))),
        ("cat", T.PickList, ["a", "b", "c", "d"] * (n // 4)),
        ("y", T.RealNN, [float(i % 2) for i in range(n)]), response="y")
    feats = VectorsCombiner().set_input(
        RealVectorizer().set_input(x).get_output(),
        OneHotVectorizer(top_k=5, min_support=1).set_input(cat).get_output(),
    ).get_output()
    pred = OpLogisticRegression(reg_param=0.1).set_input(y, feats).get_output()
    return OpWorkflow().set_input_dataset(ds).set_result_features(pred).train()


def _percentile(sorted_ms, p):
    if not sorted_ms:
        return 0.0
    i = min(len(sorted_ms) - 1, int(p / 100.0 * len(sorted_ms)))
    return sorted_ms[i]


def _replica_summary(serve_snapshot, elapsed):
    """Per-replica QPS + latency digest from the /metrics replicas block."""
    out = {}
    for slot, st in (serve_snapshot.get("replicas") or {}).items():
        out[slot] = {
            "device": st.get("device", ""),
            "batches": st.get("batches", 0),
            "responses": st.get("responses", 0),
            "qps": (round(st.get("responses", 0) / elapsed, 1)
                    if elapsed else 0.0),
            "p99_ms": (st.get("request_latency") or {}).get("p99_ms", 0.0),
        }
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model-location", default=None,
                   help="saved model dir (default: train a demo model)")
    p.add_argument("--record", default=None,
                   help="JSON record to score (default matches demo model)")
    p.add_argument("--concurrency", type=int, default=64)
    p.add_argument("--duration", type=float, default=10.0, help="seconds")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--queue-size", type=int, default=1024)
    p.add_argument("--replicas", type=int, default=None,
                   help="per-chip model replicas (default: "
                        "TMOG_SERVE_REPLICAS or one per device)")
    p.add_argument("--tenants", type=int, default=0, metavar="N",
                   help="multi-tenant mode: deploy the model as N named "
                        "tenants sharing the plane; client threads "
                        "round-robin tenants and the JSONL gains per-tenant "
                        "QPS/p99 (0 = classic single-tenant probe)")
    p.add_argument("--compile-cache", default=None,
                   help="persistent AOT executable cache dir (sets "
                        "TMOG_COMPILE_CACHE for this run)")
    p.add_argument("--no-record", action="store_true",
                   help="skip the telemetry JSONL run record")
    p.add_argument("--drift-shift", type=float, default=0.0,
                   help="add this offset to every numeric field of the "
                        "scored record partway through the run (synthetic "
                        "covariate drift for the continual-learning gauge)")
    p.add_argument("--drift-after", type=float, default=None,
                   help="seconds into the run before the shift kicks in "
                        "(default: half the duration)")
    p.add_argument("--kill-replica", type=int, default=None, metavar="N",
                   help="chaos: inject a permanent scoring fault into "
                        "replica slot N partway through the run, clear it "
                        "after --kill-duration, and report the supervisor's "
                        "recovery latency (circuit re-close) in the JSONL")
    p.add_argument("--kill-after", type=float, default=None,
                   help="seconds into the run before the kill (default: a "
                        "third of the duration)")
    p.add_argument("--kill-duration", type=float, default=2.0,
                   help="seconds the injected fault stays armed")
    p.add_argument("--poison-rate", type=float, default=0.0,
                   help="fraction of requests sent with a malformed record "
                        "(NaN / non-scalar / text garbage in a numeric "
                        "field, cycling); each must come back as a per-row "
                        "HTTP 422, never a 500 and never a breaker trip")
    args = p.parse_args(argv)
    if not 0.0 <= args.poison_rate < 1.0:
        p.error("--poison-rate must be in [0, 1)")

    if args.compile_cache:
        os.environ["TMOG_COMPILE_CACHE"] = args.compile_cache

    from transmogrifai_tpu import obs
    from transmogrifai_tpu.serve import ModelRegistry, ModelServer
    from transmogrifai_tpu.serve import compile_cache

    if args.model_location:
        from transmogrifai_tpu.workflow.model import load_model

        model = load_model(args.model_location)
    else:
        model = _train_demo_model()
    record = json.loads(args.record) if args.record else {"x": 0.7, "cat": "b"}

    registry = ModelRegistry(max_batch=args.max_batch, replicas=args.replicas)
    server = ModelServer(registry, port=0, max_batch=args.max_batch,
                         max_wait_ms=args.max_wait_ms,
                         queue_size=args.queue_size)
    compile_cache.reset_cache_stats()
    t_warm = time.perf_counter()
    if args.tenants > 0:
        # same model object per tenant: first warm compiles, the rest warm
        # from the in-process memo — the instant-warm activation path
        for i in range(args.tenants):
            registry.deploy(model, tenant=f"t{i:02d}")
    else:
        registry.deploy(model)
    warm_s = time.perf_counter() - t_warm
    warm_cache = compile_cache.cache_stats()
    # serve-path drift sketch: scored records fold into per-feature
    # histograms compared against the model's training baselines, surfaced
    # as /metrics "drift" (the continual-learning trigger signal)
    from transmogrifai_tpu.continual import ServeSketch, baselines_from_model

    server.metrics.attach_sketch(ServeSketch(baselines_from_model(model)))
    server.start()
    url = f"{server.url}/score"
    payload = json.dumps(record).encode()
    shifted = {k: (v + args.drift_shift
                   if isinstance(v, (int, float)) and not isinstance(v, bool)
                   else v) for k, v in record.items()}
    shifted_payload = json.dumps(shifted).encode()

    # poison corpus: garbage planted in the record's first numeric field
    # (Python's json emits/accepts the NaN token, so the NaN variant is a
    # true non-finite float by the time the server parses it)
    num_keys = [k for k, v in record.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)]
    pk = num_keys[0] if num_keys else "__poison__"
    poison_payloads = [
        json.dumps({**record, pk: g}).encode()
        for g in (float("nan"), ["not", "a", "scalar"], "!!poison!!")]
    poison_every = int(round(1.0 / args.poison_rate)) if args.poison_rate \
        else 0

    latencies_ms: list = []
    shed = [0]
    errors = [0]
    count = [0]
    poison_sent = [0]
    poison_422 = [0]
    lock = threading.Lock()
    stop_at = time.monotonic() + args.duration
    drift_at = stop_at - args.duration + (
        args.drift_after if args.drift_after is not None
        else args.duration / 2.0)

    def client(idx: int = 0):
        local_lat, local_shed, local_err, local_n = [], 0, 0, 0
        local_psent, local_p422, sent = 0, 0, 0
        my_url = url if not args.tenants else \
            f"{url}?tenant=t{idx % args.tenants:02d}"
        while time.monotonic() < stop_at:
            body = shifted_payload if args.drift_shift and \
                time.monotonic() >= drift_at else payload
            poisoned = poison_every and sent % poison_every == 0
            if poisoned:
                body = poison_payloads[local_psent % len(poison_payloads)]
                local_psent += 1
            sent += 1
            t0 = time.perf_counter()
            try:
                req = urllib.request.Request(my_url, data=body,
                                             headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30) as resp:
                    resp.read()
                if poisoned:
                    local_err += 1   # poison must NOT score
                else:
                    local_lat.append((time.perf_counter() - t0) * 1000.0)
                    local_n += 1
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    local_shed += 1
                    time.sleep(0.001)  # back off briefly on shed
                elif poisoned and e.code == 422:
                    local_p422 += 1   # the expected per-row rejection
                else:
                    local_err += 1
            except Exception:
                local_err += 1
        with lock:
            latencies_ms.extend(local_lat)
            shed[0] += local_shed
            errors[0] += local_err
            count[0] += local_n
            poison_sent[0] += local_psent
            poison_422[0] += local_p422

    chaos: dict = {}

    def chaos_thread():
        """Kill replica N mid-run, heal it, time the supervisor recovery."""
        from transmogrifai_tpu.resilience import inject

        slot = args.kill_replica
        sup = server.batcher.supervisor
        brk = sup.breaker(slot)
        time.sleep(args.kill_after if args.kill_after is not None
                   else args.duration / 3.0)
        inject.add_rule(f"serve.score#{slot}:fatal")
        chaos["killed_at_s"] = round(time.monotonic() - t0, 3)
        time.sleep(args.kill_duration)
        inject.clear_rules("serve.score")
        cleared = time.monotonic()
        chaos["cleared_at_s"] = round(cleared - t0, 3)
        deadline = cleared + 30.0
        while time.monotonic() < deadline:
            if brk.available:
                chaos["recovery_s"] = round(time.monotonic() - cleared, 3)
                break
            time.sleep(0.02)
        chaos["circuit"] = brk.snapshot()
        chaos["supervisor_recoveries"] = sup.recoveries

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(args.concurrency)]
    t0 = time.monotonic()
    if args.kill_replica is not None:
        if not 0 <= args.kill_replica < registry.n_replicas:
            p.error(f"--kill-replica {args.kill_replica} out of range "
                    f"(0..{registry.n_replicas - 1})")
        threads.append(threading.Thread(target=chaos_thread, daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0

    with urllib.request.urlopen(f"{server.url}/metrics", timeout=10) as resp:
        server_metrics = json.loads(resp.read())
    server.stop()

    latencies_ms.sort()
    out = {
        "probe": "serve",
        "concurrency": args.concurrency,
        "duration_s": round(elapsed, 3),
        "warmup_s": round(warm_s, 3),
        "replicas": registry.n_replicas,
        "responses": count[0],
        "throughput_rps": round(count[0] / elapsed, 1) if elapsed else 0.0,
        "client_shed": shed[0],
        "client_errors": errors[0],
        "p50_ms": round(_percentile(latencies_ms, 50), 3),
        "p95_ms": round(_percentile(latencies_ms, 95), 3),
        "p99_ms": round(_percentile(latencies_ms, 99), 3),
        "batch_occupancy_mean": server_metrics["serve"]["batch_occupancy_mean"],
        "replica_stats": _replica_summary(server_metrics["serve"], elapsed),
        "compile_cache": {k: warm_cache.get(k) for k in
                          ("hits", "misses", "compiles", "compile_s",
                           "load_s", "saves", "save_errors")},
        "drift_shift": args.drift_shift,
        "drift": server_metrics["serve"].get("drift", {}),
        "tenants": args.tenants,
        "tenant_stats": {
            t: {"responses": st.get("responses", 0),
                "shed": st.get("shed", 0),
                "qps": (round(st.get("responses", 0) / elapsed, 1)
                        if elapsed else 0.0),
                "p99_ms": (st.get("request_latency") or {}).get("p99_ms",
                                                                0.0)}
            for t, st in (server_metrics["serve"].get("tenants")
                          or {}).items()},
        "continual": server_metrics.get("continual", {}),
        "server_metrics": server_metrics["serve"],
    }
    # data-plane health: ~0 on a clean corpus, so the perf gate's
    # lower-is-better policy flags an over-rejecting contract
    srv = server_metrics["serve"]
    reqs = max(1, srv.get("requests", 0))
    out["quarantine_rate"] = round(srv.get("quarantined", 0) / reqs, 6)
    out["data_fault_fraction"] = round(srv.get("data_faults", 0) / reqs, 6)
    if args.poison_rate:
        out["poison"] = {
            "rate": args.poison_rate,
            "poison_sent": poison_sent[0],
            "poison_422": poison_422[0],
            "data_faults": srv.get("data_faults", 0),
            "quarantined": srv.get("quarantined", 0),
            "quarantine": server_metrics.get(
                "resilience", {}).get("quarantined", 0),
        }
    if args.kill_replica is not None:
        out["chaos"] = {"kill_replica": args.kill_replica,
                        "kill_duration_s": args.kill_duration, **chaos}
        out["resilience"] = server_metrics.get("resilience", {})
    print(json.dumps(out))
    if not args.no_record:
        # schema-versioned run record (context + full obs snapshot included)
        obs.write_record("probe_serve", extra=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
