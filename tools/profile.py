"""Kernel micro-profiler (dev tool): RF/GBT hot shapes, one entry point.

Consolidates the former ``profile_trees.py`` / ``profile_trees2.py`` /
``profile_trees3.py`` / ``profile_trace.py`` into subcommands:

- ``trees``        — the RF depth/frontier/chunk matrix + GBT batch cases at
  the Titanic hot shapes (n=891, d=24, 32 bins), mean-of-reps timing;
- ``trees-beam``   — the histogram-precision (TMOG_HIST_BF16) and frontier-
  beam variants at depth 12;
- ``trees-stats``  — min/median timing of the three sweep-representative RF
  cases + the GBT batch case (noise-robust numbers for before/after diffs);
- ``trace``        — one warmed depth-12 forest build under
  ``jax.profiler.trace`` (XLA-level, for TensorBoard);
- ``fused``        — per-fragment device-time profile of the fused Titanic
  sweep (the former ``profile_fused.py``): the full spec, each fragment
  kind alone, and each forest depth group alone;
- ``roofline``     — the launch ledger over the fused Titanic sweep:
  per-launch FLOPs + bytes-accessed vs the device peaks, per-family MFU
  decomposition and compute/memory/launch-bound labels
  (transmogrifai_tpu/obs/ledger.py; set TMOG_PEAK_FLOPS /
  TMOG_PEAK_HBM_GBPS to calibrate off-TPU).

``--trace out.json`` on any subcommand additionally records obs spans
(``profile.case`` per timed case) and exports Chrome trace-event JSON
loadable in Perfetto — the span tracer the rest of the repo shares
(transmogrifai_tpu/obs).  Every run appends a ``profile`` row to the
telemetry JSONL (TMOG_TELEMETRY or ./telemetry.jsonl).
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import init_backend

parser = argparse.ArgumentParser(description=__doc__)
parser.add_argument("cmd", nargs="?", default="trees",
                    choices=["trees", "trees-beam", "trees-stats", "trace",
                             "fused", "roofline"])
parser.add_argument("--reps", type=int, default=0,
                    help="timing repetitions (default: 3, trees-stats 6)")
parser.add_argument("--trace", default="",
                    help="record obs spans and export Chrome trace-event "
                         "JSON here (open in Perfetto)")
cli = parser.parse_args()

init_backend()
import jax
import jax.numpy as jnp

from transmogrifai_tpu import obs
from transmogrifai_tpu.obs import trace as obs_trace
from transmogrifai_tpu.ops import trees as Tr

if cli.trace:
    obs_trace.enable(cli.trace)

# the Titanic hot shapes every sweep-kernel case below runs at
n, d = 891, 24
rng = np.random.default_rng(0)
X = rng.normal(size=(n, d)).astype(np.float32)
y = (rng.random(n) < 0.4).astype(np.float32)
Xb, _ = Tr.quantize(X, 32)
G = -y[:, None]
H = np.ones(n, np.float32)


def timed_mean(fn, label, reps):
    with obs_trace.span("profile.case", case=label, reps=reps):
        fn()  # compile
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(fn())
        dt = (time.perf_counter() - t0) / reps
    print(f"{label:48s} {dt * 1e3:9.1f} ms")
    return dt


def timed_minmed(fn, label, reps):
    with obs_trace.span("profile.case", case=label, reps=reps):
        fn()  # compile
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            ts.append(time.perf_counter() - t0)
    print(f"{label:44s} min {min(ts) * 1e3:8.1f}  "
          f"med {float(np.median(ts)) * 1e3:8.1f} ms")
    return min(ts)


def rf_runner(TT, depth, frontier, chunk):
    wt = rng.poisson(1.0, size=(TT, n)).astype(np.float32)
    fm = (rng.random((TT, d)) < 0.3).astype(np.float32)
    mcw = np.full(TT, 10.0, np.float32)
    a = [jnp.asarray(v) for v in (Xb, G, H, wt, fm, mcw)]

    def run():
        return Tr.fit_forest_chunked(*a, max_depth=depth, n_bins=32,
                                     chunk=chunk, frontier=frontier)

    return run


def rf_case(timer, TT, depth, frontier, chunk, label, reps, env=None):
    if env:
        for k, v in env.items():
            os.environ[k] = v
    try:
        return timer(rf_runner(TT, depth, frontier, chunk), label, reps)
    finally:
        if env:
            for k in env:
                os.environ.pop(k)


def gbt_runner(n_rounds=200, max_depth=10, frontier=64, B=6):
    rw = np.ones((n_rounds, n), np.float32)
    fms = np.ones((n_rounds, d), np.float32)
    kw = dict(loss="logistic", n_rounds=n_rounds, max_depth=max_depth,
              n_bins=32, frontier=frontier,
              eta_b=jnp.full(B, 0.02), reg_lambda_b=jnp.full(B, 1.0),
              gamma_b=jnp.full(B, 0.8), min_child_weight_b=jnp.full(B, 1.0))
    a = [jnp.asarray(v) for v in (Xb, y, np.ones((B, n), np.float32),
                                  rw, fms)]

    def run():
        return Tr.fit_gbt_batch(a[0], a[1], a[2], a[3], a[4], **kw)

    return run


def cmd_trees(reps):
    """The sweep-representative RF matrix + GBT batch cases (means)."""
    from transmogrifai_tpu.ops.trees import forest_chunk_size

    for depth, frontier in ((3, 8), (6, 64), (12, 128)):
        cs = forest_chunk_size(depth, 32, d, 1, frontier)
        TT = 900
        chunk = min(cs, TT)
        TTp = TT + ((-TT) % chunk)
        rf_case(timed_mean, TTp, depth, frontier, chunk,
                f"RF d={depth} M={frontier} TT={TTp} chunk={chunk}", reps)
    rf_case(timed_mean, 900, 12, 128, 900, "RF d=12 M=128 one chunk of 900",
            reps)
    rf_case(timed_mean, 900, 12, 128, 300, "RF d=12 M=128 chunk=300", reps)
    rf_case(timed_mean, 896, 12, 128, 128, "RF d=12 M=128 chunk=128", reps)
    rf_case(timed_mean, 900, 12, 128, 900, "RF d=12 segsum one chunk", reps,
            env={"TMOG_HIST_MATMUL": "0"})
    timed_mean(gbt_runner(n_rounds=200),
               "XGB batch=6 rounds=200 d=10 M=64", reps)
    timed_mean(gbt_runner(n_rounds=20),
               "XGB batch=6 rounds=20 d=10 M=64", reps)


def cmd_trees_beam(reps):
    """Histogram precision (bf16 vs f32) and frontier-beam width variants."""
    rf_case(timed_mean, 900, 12, 128, 900, "RF d=12 M=128 (bf16 mm)", reps)
    rf_case(timed_mean, 900, 12, 128, 900, "RF d=12 M=128 f32 mm", reps,
            env={"TMOG_HIST_BF16": "0"})
    rf_case(timed_mean, 900, 12, 64, 900, "RF d=12 M=64 beam", reps)
    rf_case(timed_mean, 900, 12, 32, 900, "RF d=12 M=32 beam", reps)
    rf_case(timed_mean, 900, 8, 128, 900, "RF d=8 M=128", reps)
    rf_case(timed_mean, 112, 12, 128, 112, "RF d=12 M=128 TT=112", reps)


def cmd_trees_stats(reps):
    """min/median of the three sweep-representative cases (diff-stable)."""
    rf_case(timed_minmed, 900, 3, 8, 900, "RF d=3  M=8   TT=900", reps)
    rf_case(timed_minmed, 900, 6, 64, 900, "RF d=6  M=64  TT=900", reps)
    rf_case(timed_minmed, 900, 12, 128, 900, "RF d=12 M=128 TT=900", reps)
    timed_minmed(gbt_runner(n_rounds=200),
                 "XGB batch=6 rounds=200 d=10 M=64", reps)


def cmd_trace(reps):
    """One warmed depth-12 forest build under jax.profiler.trace."""
    run = rf_runner(900, 12, 128, 900)
    jax.block_until_ready(run())
    out = "/tmp/jaxtrace"
    with jax.profiler.trace(out):
        with obs_trace.span("profile.case", case="RF d=12 jax.profiler"):
            jax.block_until_ready(run())
    print(f"trace done -> {out}")


def cmd_fused(reps):
    """Per-fragment device time of the fused Titanic sweep (the folded-in
    ``profile_fused.py``): ALL, each fragment kind alone, each forest
    depth group alone — at the real selector shapes."""
    from bench import make_selector, titanic_arrays
    from transmogrifai_tpu.impl.sweep_fragments import build_sweep_plan
    from transmogrifai_tpu.ops.sweep import run_sweep

    Xt, yt = titanic_arrays()
    sel = make_selector()
    v = sel.validator
    train_w, val_mask = v.make_folds(len(yt), None)
    prep_w = sel.splitter.prepare_weights(yt)
    train_w = train_w * prep_w[None, :].astype(np.float32)
    val_mask = val_mask & (prep_w > 0)[None, :]
    plan = build_sweep_plan(sel.models, Xt, yt, train_w, v.evaluator)
    if plan is None:
        print("default grid did not build a fused plan; nothing to profile")
        return
    full = plan.spec

    def time_spec(name, frags):
        # keep the global candidate tuple: the metrics tensor stays sized by
        # the full spec; scores for absent candidates stay zero, harmless
        spec = (full[0], frags, full[2])
        with obs_trace.span("profile.case", case=name, reps=reps):
            t0 = time.perf_counter()
            m = run_sweep(spec, plan.X, plan.xbs, plan.y, train_w, val_mask,
                          plan.blob)
            np.asarray(m)
            warm = time.perf_counter() - t0
            t0 = time.perf_counter()
            for r in range(reps):
                tw = train_w * (1.0 + 1e-7 * r)  # new buffer: defeat memo
                m = run_sweep(spec, plan.X, plan.xbs, plan.y, tw, val_mask,
                              plan.blob)
                np.asarray(m)
            dt = (time.perf_counter() - t0) / reps
        print(f"{name:44s} warm={warm:7.2f}s steady={dt * 1e3:9.1f} ms")
        return dt

    frags = full[1]
    by_kind = {}
    for f in frags:
        by_kind.setdefault(f[0], []).append(f)
    time_spec("ALL", frags)
    for kind, fs in by_kind.items():
        time_spec(f"only:{kind}", tuple(fs))
    if "forest" in by_kind:
        groups = by_kind["forest"][0][2]
        for g in groups:
            frag = ("forest", by_kind["forest"][0][1], (g,))
            time_spec(f"forest depth={g[1]} frontier={g[9]} chunk={g[11]}",
                      (frag,))


def cmd_roofline(reps):
    """Launch ledger + roofline/MFU decomposition of the fused Titanic
    sweep: reps selector fits with FLOPs+bytes accounting and the launch
    ledger on, then the per-family report (obs/ledger.format_report)."""
    from bench import make_selector, titanic_arrays
    from transmogrifai_tpu.obs import ledger
    from transmogrifai_tpu.utils import flops

    Xt, yt = titanic_arrays()
    sel = make_selector()
    sel.find_best_estimator(Xt, yt)  # warmup: compile everything first
    flops.enable()
    flops.reset()
    ledger.enable()
    ledger.reset()
    trace_was_on = obs_trace.enabled()
    if not trace_was_on:
        obs_trace.enable(path=None)
    t0 = time.perf_counter()
    with obs_trace.span("profile.window", reps=reps):
        for r in range(reps):
            sel2 = make_selector(seed=100 + r)
            sel2.find_best_estimator(Xt, yt)
    wall = time.perf_counter() - t0
    if not trace_was_on:
        obs_trace.disable()
    flops.disable()
    try:
        roof = ledger.ledger_report(window_wall_s=wall,
                                    device_kind=jax.devices()[0].device_kind,
                                    platform=jax.devices()[0].platform,
                                    reps=reps)
    except ValueError:
        print("ledger is empty (cost_analysis unavailable?); no report")
        return None
    finally:
        ledger.disable()
    print(ledger.format_report(roof))
    return roof


_roof = None
if cli.cmd == "trees":
    cmd_trees(cli.reps or 3)
elif cli.cmd == "trees-beam":
    cmd_trees_beam(cli.reps or 3)
elif cli.cmd == "trees-stats":
    cmd_trees_stats(cli.reps or 6)
elif cli.cmd == "fused":
    cmd_fused(cli.reps or 5)
elif cli.cmd == "roofline":
    _roof = cmd_roofline(cli.reps or 3)
else:
    cmd_trace(cli.reps or 1)

if cli.trace:
    print(f"obs trace -> {obs_trace.export(cli.trace)}")
_extra = {"cmd": cli.cmd}
if _roof:
    _extra["roofline"] = _roof
    _extra["mfu_decomposition"] = _roof["mfu_decomposition"]
obs.write_record("profile", extra=_extra)
