"""Closed-loop continual-learning demo with synthetic covariate drift.

Trains a champion with a full (cold) ModelSelector sweep on "era A" data,
deploys it to a ModelRegistry, then drives era-B traffic — the numeric
feature shifted by ``--shift`` — through the micro-batcher so the serve-path
drift sketch fills up.  The RetrainController sees the JS divergence breach,
triggers a warm-started retrain on the recent window (the selector grid
pruned to the incumbent's neighborhood), gates the challenger against the
champion on the window's trailing holdout, and promotes it via the rolling
zero-gap hot-swap.  With ``--force-regression`` the freshly promoted
challenger is then sabotaged (its score paths raise), post-swap traffic
regresses, and the loop rolls back to the champion.

Prints one JSON line — cold vs warm sweep wall, pruned vs full candidate
counts, every loop decision, and capacity samples proving the swap never
dropped to zero replicas — and appends it as a schema-versioned JSONL run
record (kind="continual_loop").

    python tools/continual_loop.py --rows 192 --shift 3.0
    python tools/continual_loop.py --force-regression
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _era_values(n: int, shift: float):
    """(x, cat, y) lists for one era: label flips where x crosses the era's
    own center, so a model fit on era A is genuinely wrong about era B."""
    import numpy as np

    xs = list(np.linspace(-2.0, 2.0, n) + shift)
    cats = (["a", "b", "c", "d"] * ((n + 3) // 4))[:n]
    ys = [1.0 if x > shift else 0.0 for x in xs]
    return xs, cats, ys


def _build(n: int, shift: float):
    """(dataset, (x, cat, y) features) for one era."""
    import transmogrifai_tpu.types as T
    from transmogrifai_tpu.testkit import TestFeatureBuilder

    xs, cats, ys = _era_values(n, shift)
    return TestFeatureBuilder.of(("x", T.Real, xs), ("cat", T.PickList, cats),
                                 ("y", T.RealNN, ys), response="y")


def _workflow(ds, features, num_folds: int):
    """Fresh selector workflow over (x, cat) -> y on ``ds``."""
    from transmogrifai_tpu import OpWorkflow
    from transmogrifai_tpu.impl.feature.vectorizers import (
        OneHotVectorizer, RealVectorizer, VectorsCombiner)
    from transmogrifai_tpu.impl.selector.factories import (
        BinaryClassificationModelSelector)

    x, cat, y = features
    feats = VectorsCombiner().set_input(
        RealVectorizer().set_input(x).get_output(),
        OneHotVectorizer(top_k=5, min_support=1).set_input(cat).get_output(),
    ).get_output()
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=num_folds, splitter=None)
    pred = sel.set_input(y, feats).get_output()
    return OpWorkflow().set_input_dataset(ds).set_result_features(pred)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rows", type=int, default=192, help="rows per era")
    p.add_argument("--shift", type=float, default=3.0,
                   help="era-B covariate shift on x")
    p.add_argument("--num-folds", type=int, default=2)
    p.add_argument("--force-regression", action="store_true",
                   help="sabotage the promoted challenger to demonstrate "
                        "the post-swap rollback path")
    p.add_argument("--no-record", action="store_true",
                   help="skip the telemetry JSONL run record")
    args = p.parse_args(argv)

    from transmogrifai_tpu import obs
    from transmogrifai_tpu.continual import (ContinualLoop, ControllerConfig,
                                             GateConfig, RetrainController,
                                             ServeSketch, baselines_from_model)
    from transmogrifai_tpu.evaluators import Evaluators
    from transmogrifai_tpu.serve import ModelRegistry, ServeMetrics
    from transmogrifai_tpu.serve.batcher import MicroBatcher

    # ---- era A: cold full-grid sweep -> champion ---------------------------
    ds_a, feats_a = _build(args.rows, 0.0)
    wf_a = _workflow(ds_a, feats_a, args.num_folds)
    sel = next(s for s in wf_a.stages
               if getattr(s, "is_model_selector", False))
    cold_candidates = sum(len(g) for _, g in sel.models)
    t0 = time.perf_counter()
    champion = wf_a.train()
    cold_wall = time.perf_counter() - t0
    metrics = ServeMetrics()
    registry = ModelRegistry(max_batch=32, metrics=metrics)
    registry.deploy(champion, version="champion")
    metrics.attach_sketch(ServeSketch(baselines_from_model(champion)))

    # ---- era B traffic through the batcher (fills the drift sketch) -------
    capacity_samples = []

    def sample_capacity():
        capacity_samples.append(
            sum(1 for i in range(registry.n_replicas)
                if registry.replica(i) is not None))

    batcher = MicroBatcher(registry, max_batch=32, metrics=metrics)
    batcher.start()
    xs, cats, _ = _era_values(args.rows, args.shift)
    futures = [batcher.submit({"x": float(x), "cat": c})
               for x, c in zip(xs, cats)]
    for f in futures:
        f.result(60.0)
    sample_capacity()

    # ---- the loop: drift -> warm retrain -> gate -> rolling swap -----------
    ds_b, feats_b = _build(args.rows, args.shift)
    controller = RetrainController(ControllerConfig(
        threshold=0.25, hysteresis=1, cooldown_s=0.0, min_count=16))
    loop = ContinualLoop(
        registry, metrics,
        workflow_factory=lambda ds: _workflow(ds, feats_b, args.num_folds),
        window_provider=lambda: ds_b,
        evaluator=Evaluators.BinaryClassification.auPR(),
        controller=controller, gate=GateConfig(epsilon=0.05),
        holdout_fraction=0.25)
    outcome = loop.run_once(version="challenger")
    sample_capacity()

    rollback_version = None
    if args.force_regression and outcome.get("outcome") == "promote":
        entry = registry.active()
        def _boom(*a, **k):
            raise RuntimeError("injected post-swap regression")
        entry.batch = _boom   # forces every replica off the AOT path...
        entry.row = _boom     # ...and poisons the per-record fallback too
        for x, c in zip(xs, cats):
            try:
                batcher.submit({"x": float(x), "cat": c}).result(60.0)
            except Exception:
                pass
        rollback_version = loop.check_rollback()
        sample_capacity()
    batcher.stop()

    retrain = outcome.get("retrain") or {}
    out = {
        "probe": "continual_loop",
        "rows": args.rows, "shift": args.shift,
        "cold_sweep_wall_s": round(cold_wall, 4),
        "cold_candidates": cold_candidates,
        "warm_retrain_wall_s": retrain.get("wall_s"),
        "pruned_candidates": retrain.get("pruned_candidates"),
        "full_candidates": retrain.get("full_candidates"),
        "outcome": outcome.get("outcome"),
        "gate": outcome.get("gate"),
        "decision": outcome.get("decision"),
        "promoted_version": outcome.get("version"),
        "rollback_version": rollback_version,
        "capacity_samples": capacity_samples,
        "capacity_never_zero": bool(capacity_samples)
        and min(capacity_samples) > 0,
        "drift": metrics.snapshot().get("drift", {}),
        "continual": obs.REGISTRY.scope("continual").snapshot(),
    }
    print(json.dumps(out))
    if not args.no_record:
        obs.write_record("continual_loop", extra=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
