import os, time
import numpy as np
from bench import init_backend
init_backend()
import jax, jax.numpy as jnp
from transmogrifai_tpu.ops import trees as Tr

n, d = 891, 24
rng = np.random.default_rng(0)
X = rng.normal(size=(n, d)).astype(np.float32)
y = (rng.random(n) < 0.4).astype(np.float32)
Xb, edges = Tr.quantize(X, 32)
G = -y[:, None]; H = np.ones(n, np.float32)

def t(fn, reps=3):
    fn()
    t0 = time.perf_counter()
    for _ in range(reps): jax.block_until_ready(fn())
    return (time.perf_counter() - t0) / reps

def rf_case(TT, depth, frontier, chunk, label, env=None):
    if env:
        for k, v in env.items(): os.environ[k] = v
    wt = rng.poisson(1.0, size=(TT, n)).astype(np.float32)
    fm = (rng.random((TT, d)) < 0.3).astype(np.float32)
    mcw = np.full(TT, 10.0, np.float32)
    a = [jnp.asarray(v) for v in (Xb, G, H, wt, fm, mcw)]
    def run():
        return Tr.fit_forest_chunked(*a, max_depth=depth, n_bins=32,
                                     chunk=chunk, frontier=frontier)
    dt = t(run)
    print(f"{label:48s} {dt*1e3:9.1f} ms")
    if env:
        for k in env: os.environ.pop(k)

rf_case(900, 12, 128, 900, "RF d=12 M=128 (bf16 mm)")
rf_case(900, 12, 128, 900, "RF d=12 M=128 f32 mm", {"TMOG_HIST_BF16": "0"})
rf_case(900, 12, 64, 900,  "RF d=12 M=64 beam")
rf_case(900, 12, 32, 900,  "RF d=12 M=32 beam")
rf_case(900, 8, 128, 900,  "RF d=8 M=128")
rf_case(112, 12, 128, 112, "RF d=12 M=128 TT=112")
