"""Microbench: histogram-matmul formulations on TPU (dev tool).

Hypothesis: the vmapped per-tree [m, n] @ [n, dBc] batched-GEMM lowers
poorly at batch=chunk; flattening the tree axis into the GEMM M dimension
([T*m, n] @ [n, dBc]) should run near MXU speed.
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import init_backend

platform, _fb = init_backend()
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

n, dBc, m, T = 891, 1536, 128, 635
rng = np.random.default_rng(0)
Og = jnp.asarray(rng.normal(size=(n, dBc)).astype(np.float32))
slot = jnp.asarray(rng.integers(0, m, size=(T, n)))
w = jnp.asarray(rng.random((T, n)).astype(np.float32))


@jax.jit
def batched(slot, w):
    S = jax.nn.one_hot(slot, m, dtype=jnp.float32)         # [T, n, m]
    Sw = S * w[:, :, None]
    f = jax.vmap(lambda s: lax.dot_general(s, Og, (((0,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32))
    return f(Sw.transpose(0, 1, 2))                        # [T, m, dBc]


@jax.jit
def flat(slot, w):
    S = jax.nn.one_hot(slot, m, dtype=jnp.float32)         # [T, n, m]
    Sw = (S * w[:, :, None]).transpose(0, 2, 1).reshape(T * m, n)
    return (Sw @ Og).reshape(T, m, dBc)


@jax.jit
def flat_bf16(slot, w):
    S = jax.nn.one_hot(slot, m, dtype=jnp.bfloat16)
    Sw = (S * w.astype(jnp.bfloat16)[:, :, None]).transpose(0, 2, 1).reshape(T * m, n)
    return lax.dot_general(Sw, Og.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32).reshape(T, m, dBc)


@jax.jit
def onehot_only(slot, w):
    S = jax.nn.one_hot(slot, m, dtype=jnp.float32)
    return (S * w[:, :, None]).sum()


results = {}


def timed(name, fn, reps=10):
    fn(slot, w).block_until_ready()
    outs = []
    t0 = time.perf_counter()
    for r in range(reps):
        outs.append(fn(slot + 0 * r, w + 1e-7 * r))
    jax.block_until_ready(outs[-1])
    dt = (time.perf_counter() - t0) / reps
    gf = 2 * T * m * n * dBc / 1e9
    print(f"{name:16s} {dt*1e3:8.2f} ms   ({gf/dt/1e3:6.2f} TFLOP/s)")
    results[name] = {"ms": round(dt * 1e3, 4),
                     "tflops": round(gf / dt / 1e3, 4)}


timed("batched-gemm", batched)
timed("flat-gemm", flat)
timed("flat-bf16", flat_bf16)
timed("onehot-only", onehot_only)

from transmogrifai_tpu import obs  # noqa: E402

obs.write_record("probe_hist_mm", extra={"report": {
    "metric": "hist_matmul_tflops", "platform": platform,
    "value": results["flat-gemm"]["tflops"],
    "shape": {"n": n, "dBc": dBc, "m": m, "T": T}, "cases": results}})
