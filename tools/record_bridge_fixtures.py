"""Record golden wire-bytes fixtures for the bridge protocol (dev tool).

Writes the EXACT bytes a conforming client sends for a canonical session —
one file per request frame sequence — to tests/fixtures/bridge/.  The
replay test (tests/test_bridge_golden.py) feeds these raw bytes to a live
server socket and validates the responses, so the protocol contract is
pinned independently of the Python client implementation: a JVM client
that produces these bytes (see bridge/scala/README.md) is conforming.

Regenerate only when the protocol intentionally changes:
    python tools/record_bridge_fixtures.py
"""
import json
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd
import pyarrow as pa

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "fixtures", "bridge")
HEADER = struct.Struct(">cI")


def frame(kind: bytes, payload: bytes) -> bytes:
    return HEADER.pack(kind, len(payload)) + payload


def jframe(obj) -> bytes:
    return frame(b"J", json.dumps(obj, sort_keys=True).encode("utf-8"))


def aframe(table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return frame(b"A", sink.getvalue().to_pybytes())


def canonical_df(n=60, seed=7) -> pd.DataFrame:
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    sex = rng.choice(["m", "f"], n)
    y = ((x1 + (sex == "m") + rng.normal(scale=0.4, size=n)) > 0.5).astype(float)
    return pd.DataFrame({"label": y, "x1": x1, "sex": sex})


SPEC = {
    "features": [
        {"name": "label", "type": "RealNN", "response": True},
        {"name": "x1", "type": "Real"},
        {"name": "sex", "type": "PickList"},
    ],
    "stages": [
        {"cls": "impl.feature.vectorizers.RealVectorizer",
         "params": {}, "inputs": ["x1"], "name": "nums"},
        {"cls": "impl.feature.vectorizers.OneHotVectorizer",
         "params": {"top_k": 5, "min_support": 1}, "inputs": ["sex"],
         "name": "cats"},
        {"cls": "impl.feature.vectorizers.VectorsCombiner",
         "params": {}, "inputs": ["nums", "cats"], "name": "vec"},
        {"cls": "impl.classification.logistic.OpLogisticRegression",
         "params": {"reg_param": 0.01}, "inputs": ["label", "vec"],
         "name": "pred"},
    ],
    "result": ["pred"],
}


def main():
    os.makedirs(OUT, exist_ok=True)
    df = canonical_df()
    table = pa.Table.from_pandas(df, preserve_index=False)

    # each fixture: the raw request bytes; expected response keys live in
    # expectations.json next to them
    fixtures = [
        ("01_ping", jframe({"op": "ping"}),
         {"ok": True, "has": ["backend", "devices"]}),
        ("02_put_data", aframe(table) + jframe({"op": "put_data",
                                                "name": "train"}),
         {"ok": True, "equals": {"rows": len(df), "cols": 3}}),
        ("03_build", jframe({"op": "build", "spec": SPEC, "name": "wf"}),
         {"ok": True, "equals": {"workflow": "wf"}}),
        ("04_train", jframe({"op": "train", "workflow": "wf",
                             "data": "train", "model": "model"}),
         {"ok": True, "equals": {"model": "model"}}),
        ("05_score", jframe({"op": "score", "model": "model",
                             "data": "train"}),
         {"ok": True, "arrow": True, "equals": {"rows": len(df)}}),
        ("06_evaluate", jframe({"op": "evaluate", "model": "model",
                                "data": "train", "evaluator": "binary",
                                "label": "label"}),
         {"ok": True, "has": ["metrics"]}),
        ("07_summary", jframe({"op": "summary", "model": "model"}),
         {"ok": True, "has": ["summary"]}),
        ("08_bad_op", jframe({"op": "no_such_op"}),
         {"ok": False, "has": ["error"]}),
        ("09_shutdown", jframe({"op": "shutdown"}), {"ok": True}),
    ]
    expect = {}
    for name, raw, exp in fixtures:
        with open(os.path.join(OUT, f"{name}.bin"), "wb") as f:
            f.write(raw)
        expect[name] = exp
    with open(os.path.join(OUT, "expectations.json"), "w") as f:
        json.dump(expect, f, indent=1, sort_keys=True)
    # the label column ships with the fixture set for score-accuracy checks
    np.save(os.path.join(OUT, "labels.npy"), df["label"].to_numpy())
    print(f"wrote {len(fixtures)} fixtures to {OUT}")


if __name__ == "__main__":
    main()
