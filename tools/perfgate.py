"""CI perf-regression gate: fresh run records vs committed baselines.

Compares run reports against the newest committed ``BENCH_r*.json`` /
``STREAM_BENCH.json`` baselines with per-metric direction (throughput up,
walls down) and a relative noise tolerance (``--tol`` /
``TMOG_PERFGATE_TOL``, default 0.25).  Exit codes: 0 pass, 1 regression,
2 no baselines found.

- ``--record PATH`` (repeatable): a report JSON (flat or the ``BENCH_r*``
  ``{"parsed": ...}`` wrapper) or a telemetry JSONL whose rows carry
  ``report`` dicts (``bench.py`` writes these).  Rows whose ``metric`` has
  no committed baseline, and platform-mismatched pairs (CPU-proxy CI run vs
  a TPU baseline), are skipped, not failed.
- With no ``--record`` (or none readable) the gate self-checks each
  baseline against itself — validating the baseline set and the policy
  table still parse — and passes.
- ``--warn-only``: print verdicts, always exit 0 (the CPU-proxy tier1 step).

No JAX import: the gate is pure JSON comparison and runs anywhere.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from transmogrifai_tpu.obs import regress  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--record", action="append", default=[],
                    help="fresh run record(s): report JSON or telemetry "
                         "JSONL (repeatable; default: self-check baselines)")
    ap.add_argument("--baseline-dir", default=None,
                    help="where the committed BENCH_*/STREAM_BENCH live "
                         "(default: the repo root)")
    ap.add_argument("--tol", type=float, default=None,
                    help="relative tolerance (default TMOG_PERFGATE_TOL "
                         f"or {regress.DEFAULT_TOL})")
    ap.add_argument("--warn-only", action="store_true",
                    help="report regressions but exit 0 (CPU-proxy CI)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdicts as one JSON object on stdout")
    args = ap.parse_args(argv)

    root = args.baseline_dir or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    baselines = regress.load_baselines(root)
    if not baselines:
        print(f"perfgate: no BENCH_r*/STREAM_BENCH baselines under {root}",
              file=sys.stderr)
        return 2
    tol = regress.default_tolerance() if args.tol is None else args.tol

    reports = []
    for path in args.record:
        got = regress.extract_reports(path)
        if not got:
            print(f"perfgate: no reports readable from {path} (skipped)")
        reports.extend(got)
    self_check = not reports
    if self_check:
        reports = [dict(rep) for _, rep in baselines.values()]

    verdicts, regressed = [], False
    for rep in reports:
        metric = rep.get("metric")
        entry = baselines.get(metric)
        if entry is None:
            verdicts.append({"metric": metric, "ok": True,
                             "skipped": "no committed baseline"})
            continue
        name, base = entry
        v = regress.compare(rep, base, tol=tol)
        v["baseline_file"] = name
        verdicts.append(v)
        regressed = regressed or not v["ok"]

    if args.json:
        print(json.dumps({"tol": tol, "self_check": self_check,
                          "warn_only": args.warn_only,
                          "regressed": regressed, "verdicts": verdicts}))
    else:
        mode = "self-check (no fresh records)" if self_check else \
            f"{len(reports)} fresh report(s)"
        print(f"perfgate: {mode}, tol={tol:g}")
        for v in verdicts:
            if v.get("skipped"):
                print(f"  {v['metric']}: SKIP ({v['skipped']})")
                continue
            for r in v["results"]:
                mark = {"ok": "ok", "improved": "OK+", "regressed": "REGRESS",
                        "skipped_missing": "-", "skipped_platform": "-"}
                ratio = "" if r["ratio"] is None else f" x{r['ratio']:g}"
                print(f"  {v['metric']}.{r['key']} [{v['baseline_file']}]: "
                      f"{r['baseline']} -> {r['current']}{ratio}  "
                      f"{mark[r['status']]}")
        print("perfgate: " + ("REGRESSION" if regressed else "pass")
              + (" (warn-only)" if regressed and args.warn_only else ""))
    if regressed and not args.warn_only:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
