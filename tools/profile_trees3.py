import os, time, sys
import numpy as np
from bench import init_backend
init_backend()
import jax, jax.numpy as jnp
from transmogrifai_tpu.ops import trees as Tr

n, d = 891, 24
rng = np.random.default_rng(0)
X = rng.normal(size=(n, d)).astype(np.float32)
y = (rng.random(n) < 0.4).astype(np.float32)
Xb, _ = Tr.quantize(X, 32)
G = -y[:, None]; H = np.ones(n, np.float32)

def t(fn, reps=6):
    fn()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return min(ts), float(np.median(ts))

def rf_case(TT, depth, frontier, chunk, label):
    wt = rng.poisson(1.0, size=(TT, n)).astype(np.float32)
    fm = (rng.random((TT, d)) < 0.3).astype(np.float32)
    mcw = np.full(TT, 10.0, np.float32)
    a = [jnp.asarray(v) for v in (Xb, G, H, wt, fm, mcw)]
    def run():
        return Tr.fit_forest_chunked(*a, max_depth=depth, n_bins=32,
                                     chunk=chunk, frontier=frontier)
    mn, md = t(run)
    print(f"{label:44s} min {mn*1e3:8.1f}  med {md*1e3:8.1f} ms")

rf_case(900, 3, 8, 900,    "RF d=3  M=8   TT=900")
rf_case(900, 6, 64, 900,   "RF d=6  M=64  TT=900")
rf_case(900, 12, 128, 900, "RF d=12 M=128 TT=900")

B = 6
rw = np.ones((200, n), np.float32)
fms = np.ones((200, d), np.float32)
args = dict(loss="logistic", n_rounds=200, max_depth=10, n_bins=32, frontier=64,
            eta_b=jnp.full(B, 0.02), reg_lambda_b=jnp.full(B, 1.0),
            gamma_b=jnp.full(B, 0.8), min_child_weight_b=jnp.full(B, 1.0))
xa = [jnp.asarray(v) for v in (Xb, y, np.ones((B, n), np.float32), rw, fms)]
def xgb():
    return Tr.fit_gbt_batch(xa[0], xa[1], xa[2], xa[3], xa[4], **args)
mn, md = t(xgb)
print(f"{'XGB batch=6 rounds=200 d=10 M=64':44s} min {mn*1e3:8.1f}  med {md*1e3:8.1f} ms")
