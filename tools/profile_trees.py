"""Micro-bench of the RF/GBT kernels at the Titanic hot shapes (dev tool)."""
import os
import sys
import time

import numpy as np

from bench import init_backend

init_backend()
import jax
import jax.numpy as jnp

from transmogrifai_tpu.ops import trees as Tr

n, d = 891, 24
rng = np.random.default_rng(0)
X = rng.normal(size=(n, d)).astype(np.float32)
y = (rng.random(n) < 0.4).astype(np.float32)
Xb, edges = Tr.quantize(X, 32)
G = -y[:, None]
H = np.ones(n, np.float32)


def t(fn, reps=3):
    fn()  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn())
    return (time.perf_counter() - t0) / reps


def rf_case(TT, depth, frontier, chunk, label):
    wt = rng.poisson(1.0, size=(TT, n)).astype(np.float32)
    fm = (rng.random((TT, d)) < 0.3).astype(np.float32)
    mcw = np.full(TT, 10.0, np.float32)
    Xb_d, G_d, H_d = jnp.asarray(Xb), jnp.asarray(G), jnp.asarray(H)
    wt_d, fm_d, mcw_d = jnp.asarray(wt), jnp.asarray(fm), jnp.asarray(mcw)

    def run():
        return Tr.fit_forest_chunked(Xb_d, G_d, H_d, wt_d, fm_d, mcw_d,
                                     max_depth=depth, n_bins=32, chunk=chunk,
                                     frontier=frontier)

    dt = t(run)
    print(f"{label:44s} {dt*1e3:9.1f} ms")
    return dt


# depth-12 group, as in the sweep: TT=1080 after pad, chunk=?
from transmogrifai_tpu.ops.trees import forest_chunk_size
for depth, frontier in ((3, 8), (6, 64), (12, 128)):
    cs = forest_chunk_size(depth, 32, d, 1, frontier)
    TT = 900
    chunk = min(cs, TT)
    TTp = TT + ((-TT) % chunk)
    rf_case(TTp, depth, frontier, chunk, f"RF d={depth} M={frontier} TT={TTp} chunk={chunk}")

# depth 12 variants
rf_case(900, 12, 128, 900, "RF d=12 M=128 one chunk of 900")
rf_case(900, 12, 128, 300, "RF d=12 M=128 chunk=300")
rf_case(896, 12, 128, 128, "RF d=12 M=128 chunk=128")

os.environ["TMOG_HIST_MATMUL"] = "0"
rf_case(900, 12, 128, 900, "RF d=12 segsum one chunk")
os.environ.pop("TMOG_HIST_MATMUL")

# XGB shape: batch 6, 200 rounds, depth 10, frontier 64
B = 6
rw = np.ones((200, n), np.float32)
fms = np.ones((200, d), np.float32)
w_batch = jnp.asarray(np.ones((B, n), np.float32))
eta_b = jnp.full(B, 0.02)
lam_b = jnp.full(B, 1.0)
gam_b = jnp.full(B, 0.8)
mcw_b = jnp.full(B, 1.0)

def xgb():
    return Tr.fit_gbt_batch(jnp.asarray(Xb), jnp.asarray(y), w_batch,
                            jnp.asarray(rw), jnp.asarray(fms), loss="logistic",
                            n_rounds=200, max_depth=10, n_bins=32, frontier=64,
                            eta_b=eta_b, reg_lambda_b=lam_b, gamma_b=gam_b,
                            min_child_weight_b=mcw_b)

print(f"{'XGB batch=6 rounds=200 d=10 M=64':44s} {t(xgb)*1e3:9.1f} ms")

def xgb20():
    return Tr.fit_gbt_batch(jnp.asarray(Xb), jnp.asarray(y), w_batch,
                            jnp.asarray(rw)[:20], jnp.asarray(fms)[:20],
                            loss="logistic",
                            n_rounds=20, max_depth=10, n_bins=32, frontier=64,
                            eta_b=eta_b, reg_lambda_b=lam_b, gamma_b=gam_b,
                            min_child_weight_b=mcw_b)

print(f"{'XGB batch=6 rounds=20 d=10 M=64':44s} {t(xgb20)*1e3:9.1f} ms")

def xgb_d5():
    return Tr.fit_gbt_batch(jnp.asarray(Xb), jnp.asarray(y), w_batch,
                            jnp.asarray(rw), jnp.asarray(fms), loss="logistic",
                            n_rounds=200, max_depth=5, n_bins=32, frontier=32,
                            eta_b=eta_b, reg_lambda_b=lam_b, gamma_b=gam_b,
                            min_child_weight_b=mcw_b)
