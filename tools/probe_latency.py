"""Measure the device round-trip latency floor on this backend (dev tool)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import init_backend

platform, fb = init_backend()
import jax
import jax.numpy as jnp
import numpy as np

print("platform:", platform)
x = jnp.ones((891, 24), jnp.float32)


@jax.jit
def trivial(a):
    return a + 1.0


@jax.jit
def loop200(a):
    def body(i, s):
        return s + a.T @ a
    return jax.lax.fori_loop(0, 200, body, jnp.zeros((24, 24), jnp.float32))


@jax.jit
def loop2000(a):
    def body(i, s):
        return s + a.T @ a
    return jax.lax.fori_loop(0, 2000, body, jnp.zeros((24, 24), jnp.float32))


results = {}


def timed(name, fn, arg, reps=20):
    fn(arg).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(reps):
        fn(arg).block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:24s} {dt*1e3:9.2f} ms")
    results[name] = round(dt * 1e3, 4)
    return dt


timed("trivial add", trivial, x)
timed("fori 200 matmul", loop200, x)
timed("fori 2000 matmul", loop2000, x)

# async pipelining: 10 trivial launches, one sync at the end
trivial(x).block_until_ready()
t0 = time.perf_counter()
outs = [trivial(x + i) for i in range(10)]
outs[-1].block_until_ready()
results["10 async trivial"] = round((time.perf_counter() - t0) * 1e3, 4)
print(f"{'10 async trivial':24s} {results['10 async trivial']:9.2f} ms total")

# host pull of a small array
y = trivial(x)
y.block_until_ready()
t0 = time.perf_counter()
for _ in range(20):
    np.asarray(y)
results["small pull (86KB)"] = round((time.perf_counter() - t0) / 20 * 1e3, 4)
print(f"{'small pull (86KB)':24s} {results['small pull (86KB)']:9.2f} ms")

# device_put of the same
arr = np.ones((891, 24), np.float32)
t0 = time.perf_counter()
for _ in range(20):
    jax.device_put(arr).block_until_ready()
results["device_put (86KB)"] = round((time.perf_counter() - t0) / 20 * 1e3, 4)
print(f"{'device_put (86KB)':24s} {results['device_put (86KB)']:9.2f} ms")

from transmogrifai_tpu import obs  # noqa: E402

obs.write_record("probe_latency", extra={"report": {
    "metric": "device_roundtrip_latency_ms", "platform": platform,
    "value": results["trivial add"], "cases_ms": results}})
