"""Per-family wall-clock profile of the Titanic default sweep (dev tool)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import init_backend, titanic_arrays

platform, fb = init_backend()
print("platform:", platform, fb)

from transmogrifai_tpu.evaluators.classification import OpBinaryClassificationEvaluator
from transmogrifai_tpu.impl.classification.logistic import OpLogisticRegression
from transmogrifai_tpu.impl.classification.trees import (
    OpRandomForestClassifier, OpXGBoostClassifier)
from transmogrifai_tpu.impl.selector import defaults as D
from transmogrifai_tpu.impl.tuning.validators import OpCrossValidation

X, y = titanic_arrays()
print("X", X.shape)

ev = OpBinaryClassificationEvaluator()


def timed(name, candidates, reps=3):
    cv = OpCrossValidation(ev, num_folds=3, seed=42)
    t0 = time.perf_counter()
    cv.validate(candidates, X, y)
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    for r in range(reps):
        cv = OpCrossValidation(ev, num_folds=3, seed=100 + r)
        cv.validate(candidates, X, y)
    dt = (time.perf_counter() - t0) / reps
    n = sum(len(g) for _, g in candidates)
    print(f"{name:30s} grids={n:3d} warm={warm:7.2f}s steady={dt:7.3f}s"
          f"  ({3*n/dt:8.1f} models/s)")
    return dt


rf = D.random_forest_grid()
by_depth = {}
for g in rf:
    by_depth.setdefault(g["max_depth"], []).append(g)

timed("LR x8", [(OpLogisticRegression(), D.logistic_regression_grid())])
for dep, gs in sorted(by_depth.items()):
    timed(f"RF depth={dep} x{len(gs)}", [(OpRandomForestClassifier(), gs)])
timed("RF all x18", [(OpRandomForestClassifier(), rf)])
timed("XGB x2", [(OpXGBoostClassifier(), D.xgboost_grid())])
