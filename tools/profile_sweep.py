"""Per-family wall-clock profile of the Titanic default sweep (dev tool).

``--shards N`` instead partitions the default fused spec with the SAME cost
model the multi-chip sweep uses (parallel/spec_partition) and prints
predicted vs MEASURED per-shard cost — each shard run sequentially on one
device — so partitioner balance regressions are diagnosable without a pod.

``--data-shards D`` (optionally with ``--shards M``) launches the REAL
row-sharded sweep on a (D x M) mesh of local devices and prints, per model
column, predicted vs measured wall plus the per-axis collective bytes and
the replicated-vs-rowsharded peak per-device X/y bytes — the memory claim
the data axis exists to make.  On CPU use
``XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""
import argparse
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import init_backend, titanic_arrays

args = argparse.ArgumentParser(description=__doc__)
args.add_argument("--shards", type=int, default=0,
                  help="partition the default grid into N cost-balanced "
                       "shards and print predicted vs measured per-shard "
                       "cost (0 = legacy per-family profile)")
args.add_argument("--data-shards", type=int, default=0,
                  help="row-shard the default sweep over a (D x max(shards,1)) "
                       "mesh and print per-axis collective bytes + "
                       "replicated-vs-rowsharded peak per-device bytes")
args.add_argument("--costmodel", action="store_true",
                  help="predict-before-compile: load the trained cost model "
                       "(TMOG_COSTMODEL_PATH) and print predicted per-shard "
                       "wall BEFORE compiling, then predicted-vs-measured "
                       "error (MAPE, makespan ratio) after the run")
args = args.parse_args()

platform, fb = init_backend()
print("platform:", platform, fb)

from transmogrifai_tpu.evaluators.classification import OpBinaryClassificationEvaluator
from transmogrifai_tpu.impl.classification.logistic import OpLogisticRegression
from transmogrifai_tpu.impl.classification.trees import (
    OpRandomForestClassifier, OpXGBoostClassifier)
from transmogrifai_tpu.impl.selector import defaults as D
from transmogrifai_tpu.impl.tuning.validators import OpCrossValidation

X, y = titanic_arrays()
print("X", X.shape)

ev = OpBinaryClassificationEvaluator()


def timed(name, candidates, reps=3):
    cv = OpCrossValidation(ev, num_folds=3, seed=42)
    t0 = time.perf_counter()
    cv.validate(candidates, X, y)
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    for r in range(reps):
        cv = OpCrossValidation(ev, num_folds=3, seed=100 + r)
        cv.validate(candidates, X, y)
    dt = (time.perf_counter() - t0) / reps
    n = sum(len(g) for _, g in candidates)
    print(f"{name:30s} grids={n:3d} warm={warm:7.2f}s steady={dt:7.3f}s"
          f"  ({3*n/dt:8.1f} models/s)")
    return dt


def _print_gbt_telemetry(sweep_ops) -> None:
    """Critical-path telemetry: sequential GBT chain + histogram subtraction."""
    from transmogrifai_tpu.utils import flops
    chains = [l["gbt_chain"] for l in sweep_ops.run_stats()["launches"]
              if l.get("gbt_chain")]
    if chains:
        ch = max(chains, key=lambda c: c["levels"])
        print(f"gbt chain: {ch['steps']} sequential boosting steps = "
              f"{ch['levels']} levels (TMOG_GBT_ROUND_COLLAPSE shortens)")
    hs = flops.hist_subtracted_totals()
    if hs.get("levels"):
        print(f"hist subtraction: {hs['levels']} level-builds halved, "
              f"~{hs['flops_avoided']:,} hist flops avoided "
              "(TMOG_HIST_SUBTRACT=0 disables)")


def _print_hedge_telemetry(sweep_ops) -> dict:
    """Straggler-defense telemetry: hedges fired, discarded loser wall, and
    the per-device health EWMAs feeding the next partition.  Returns the
    dict that rides in the run's JSONL record."""
    from transmogrifai_tpu.resilience import health as _health

    stats = sweep_ops.run_stats()
    out = {"hedges_fired": int(stats.get("hedges_fired") or 0),
           "hedge_wasted_s": round(float(stats.get("hedge_wasted_s") or 0.0),
                                   4)}
    snap = _health.tracker().snapshot()
    if snap.get("devices"):
        out["device_health"] = snap
    if out["hedges_fired"]:
        print(f"hedges: {out['hedges_fired']} fired, "
              f"{out['hedge_wasted_s']:.3f}s loser wall discarded "
              "(TMOG_HEDGE=0 disables)")
    for dev, h in (snap.get("devices") or {}).items():
        if h.get("slowdown", 1.0) > 1.5:
            print(f"  device {dev}: slowdown~{h['slowdown']:.2f}x "
                  f"({h.get('observations', 0)} obs)")
    return out


def _print_pack_telemetry(sweep_ops) -> dict:
    """MFU-gap telemetry (PR 17): candidate packing + GBT pipelining.
    Returns the dict that rides in the run's JSONL record."""
    stats = sweep_ops.run_stats()
    out = {"sweep_pack_count": int(stats.get("sweep_pack_count") or 0),
           "launches_avoided": int(stats.get("launches_avoided") or 0),
           "gbt_sequential_launches":
               int(stats.get("gbt_sequential_launches") or 0)}
    if out["sweep_pack_count"]:
        packed = out["sweep_pack_count"] + out["launches_avoided"]
        print(f"packing: {packed} candidates in {out['sweep_pack_count']} "
              f"packed launches ({out['launches_avoided']} launches avoided; "
              "TMOG_SWEEP_PACK=0 disables)")
    effs = [l["gbt_chain_eff"] for l in stats.get("launches") or []
            if l.get("gbt_chain_eff")]
    if effs:
        eff = max(effs, key=lambda e: e["levels"])
        out["gbt_overlap_fraction"] = eff.get("overlap_fraction", 0.0)
        print(f"gbt pipeline: {eff['levels']} effective sequential levels "
              f"(overlap~{out['gbt_overlap_fraction']:.0%}; "
              "TMOG_GBT_PIPELINE=0 disables)")
    from transmogrifai_tpu.utils import flops
    bf = flops.bf16_hist_totals()
    if bf.get("levels"):
        print(f"bf16 hist: {int(bf['levels'])} accumulations halved, "
              f"~{int(bf['bytes_saved']):,} hist bytes avoided "
              "(TMOG_BF16_HIST=1 enables)")
        out["bf16_hist_bytes_saved"] = int(bf["bytes_saved"])
    return out


def _load_costmodel():
    """The trained artifact at TMOG_COSTMODEL_PATH, or None (with a note)."""
    from transmogrifai_tpu import costmodel as cm
    from transmogrifai_tpu.costmodel.model import CostModel

    path = cm.model_path()
    try:
        model = CostModel.load(path)
    except Exception as e:
        print(f"costmodel: cannot load {path} ({e}); train one with "
              "`python -m transmogrifai_tpu.costmodel`")
        return None
    print(f"costmodel: {path} (n_samples={model.n_samples}, "
          f"t0={model.t0:.3e})")
    return model


def profile_shards(n_shards: int, reps: int = 3,
                   use_costmodel: bool = False):
    """Predicted vs measured per-shard cost of the default 28-candidate grid.

    Returns ``(cm_eval, bubble_report, roofline)``: the predicted-vs-
    measured eval dict (MAPE, makespan ratios) when ``--costmodel``
    supplied a trained model, the timeline bubble report over the measured
    window, and the launch-ledger roofline report — all appended to the
    run's JSONL record."""
    import jax

    from transmogrifai_tpu.impl.sweep_fragments import build_sweep_plan
    from transmogrifai_tpu.obs import ledger, timeline, trace
    from transmogrifai_tpu.ops.sweep import run_sweep
    from transmogrifai_tpu.parallel.spec_partition import (partition_spec,
                                                           predicted_balance)

    cands = [(OpLogisticRegression(max_iter=50), D.logistic_regression_grid()),
             (OpRandomForestClassifier(), D.random_forest_grid()),
             (OpXGBoostClassifier(), D.xgboost_grid())]
    F = 3
    cv = OpCrossValidation(ev, num_folds=F, seed=42)
    train_w, val_mask = cv.make_folds(len(y), None)
    plan = build_sweep_plan(cands, np.ascontiguousarray(X, np.float32), y,
                            train_w, ev)
    if plan is None:
        print("default grid did not build a fused plan; nothing to profile")
        return None, None, None
    from transmogrifai_tpu.ops import sweep as sweep_ops
    from transmogrifai_tpu.utils import flops
    flops.enable()
    flops.reset()
    ledger.enable()
    ledger.reset()
    sweep_ops.reset_run_stats()
    shards = partition_spec(plan.spec, plan.blob, n_shards, plan.n_rows,
                            plan.n_features, F)
    mx, mean = predicted_balance(shards)
    print(f"shards={len(shards)} predicted max/mean={mx / max(mean, 1e-9):.3f}")
    model = _load_costmodel() if use_costmodel else None
    model_preds = []
    if model is not None:
        # predict-before-compile: the learned wall estimate exists BEFORE
        # any XLA lowering — this is what a scheduler could use to skip or
        # re-balance a pathological partition up front
        from transmogrifai_tpu.costmodel.features import shard_feature_dict
        devs = jax.devices()
        ctx = {"device_count": float(len(devs)),
               "is_tpu": 1.0 if devs[0].platform == "tpu" else 0.0}
        for i, sh in enumerate(shards):
            feat = shard_feature_dict(sh.spec, plan.n_rows, plan.n_features,
                                      F)
            feat.update(ctx)
            model_preds.append(model.predict(feat))
        print("predict-before-compile (learned):")
        for i, p in enumerate(model_preds):
            print(f"  shard {i}: wall~{p['wall_s']:.4f}s "
                  f"compile~{p['compile_s']:.2f}s "
                  f"calib~{p['calib_wall_s']:.4f}s")
    tw = np.asarray(train_w, np.float32)
    vw = np.asarray(val_mask, np.float32)
    trace_was_on = trace.enabled()
    if not trace_was_on:
        trace.enable(path=None)  # in-memory only: feed the bubble profiler
    walls = []
    t_win = time.perf_counter()
    with trace.span("profile.window", shards=len(shards), reps=reps):
        for i, sh in enumerate(shards):
            # sequential, all on the default device: isolates per-shard COST
            # (the thing the partitioner predicts) from device contention
            with trace.span("sweep.compile", shard=i):
                out = run_sweep(sh.spec, plan.X, plan.xbs, plan.y, tw, vw,
                                sh.blob)
                np.asarray(out)  # warm (compile)
            t0 = time.perf_counter()
            for _ in range(reps):
                out = run_sweep(sh.spec, plan.X, plan.xbs, plan.y, tw, vw,
                                sh.blob)
                with trace.span("sweep.gather", shard=i) as _gsp:
                    out = np.asarray(out)
                    _gsp.set(bytes=int(out.nbytes))
            walls.append((time.perf_counter() - t0) / reps)
    wall_meas = time.perf_counter() - t_win
    wmean = float(np.mean(walls))
    print(f"{'shard':>5s} {'cands':>5s} {'predicted':>12s} {'pred/mean':>9s} "
          f"{'measured_s':>10s} {'meas/mean':>9s}")
    for i, (sh, w) in enumerate(zip(shards, walls)):
        print(f"{i:5d} {sh.n_candidates:5d} {sh.cost:12.3e} "
              f"{sh.cost / max(mean, 1e-9):9.3f} {w:10.4f} "
              f"{w / max(wmean, 1e-9):9.3f}")
    print(f"measured max/mean={max(walls) / max(wmean, 1e-9):.3f}")
    cm_eval = None
    if model_preds:
        pred = np.array([p["wall_s"] for p in model_preds])
        meas = np.array(walls)
        cm_eval = {
            "mape": round(float(np.mean(np.abs(pred - meas)
                                        / np.maximum(meas, 1e-9))), 4),
            "measured_makespan_ratio": round(
                float(meas.max() / max(meas.mean(), 1e-9)), 4),
            "predicted_makespan_ratio": round(
                float(pred.max() / max(pred.mean(), 1e-9)), 4),
            "shards": len(walls),
        }
        print(f"costmodel: MAPE={cm_eval['mape']:.3f} makespan ratio "
              f"predicted={cm_eval['predicted_makespan_ratio']:.3f} "
              f"measured={cm_eval['measured_makespan_ratio']:.3f}")
    bub = None
    try:
        bub = timeline.bubble_report(window="profile.window",
                                     wall_s=wall_meas)
        print(timeline.format_report(bub))
    except ValueError as e:
        print(f"bubble report unavailable: {e}")
    roof = None
    try:
        roof = ledger.ledger_report(window_wall_s=wall_meas,
                                    device_kind=jax.devices()[0].device_kind,
                                    platform=jax.devices()[0].platform,
                                    reps=reps)
        print(ledger.format_report(roof))
    except ValueError as e:
        print(f"roofline report unavailable: {e}")
    ledger.disable()
    ledger.reset()
    if not trace_was_on:
        trace.disable()
    _print_gbt_telemetry(sweep_ops)
    flops.disable()
    return cm_eval, bub, roof


def profile_rowsharded(n_data: int, n_model: int, reps: int = 3) -> None:
    """Real (data x model) mesh launch: parity, balance, memory, traffic."""
    import jax

    from transmogrifai_tpu.impl.sweep_fragments import build_sweep_plan
    from transmogrifai_tpu.ops import sweep as sweep_ops
    from transmogrifai_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < n_data * n_model:
        print(f"need {n_data * n_model} devices for a {n_data}x{n_model} mesh, "
              f"have {len(jax.devices())} (set XLA_FLAGS="
              "--xla_force_host_platform_device_count=8 on CPU)")
        return
    cands = [(OpLogisticRegression(max_iter=50), D.logistic_regression_grid()),
             (OpRandomForestClassifier(), D.random_forest_grid()),
             (OpXGBoostClassifier(), D.xgboost_grid())]
    F = 3
    cv = OpCrossValidation(ev, num_folds=F, seed=42)
    train_w, val_mask = cv.make_folds(len(y), None)
    plan = build_sweep_plan(cands, np.ascontiguousarray(X, np.float32), y,
                            train_w, ev)
    if plan is None:
        print("default grid did not build a fused plan; nothing to profile")
        return
    from transmogrifai_tpu.utils import flops
    mesh = make_mesh(n_data=n_data, n_model=n_model)
    single = plan.run(train_w, val_mask)
    sweep_ops.reset_run_stats()
    flops.enable()
    flops.reset()
    mrs = plan.run_rowsharded(train_w, val_mask, mesh)  # warm (compiles)
    diff = np.max(np.abs(mrs - single))
    print(f"mesh {n_data}x{n_model}: parity max|diff|={diff:.3g} "
          "vs single-device fused")
    if diff > 1e-6:
        # expected on real discrete data: psum partial-sum ordering gives
        # ulp-level G/H differences that compound over a boosting group's
        # sequential rounds until a near-tied split flips (the standard
        # distributed-XGBoost nondeterminism); LR/RF stay exact.  The
        # synthetic-grid parity tests hold the 1e-6 bar.
        print("  (>1e-6: GBT split-tie flips under psum reduction order; "
              "see README 'The data axis')")
    t0 = time.perf_counter()
    for _ in range(reps):
        plan.run_rowsharded(train_w, val_mask, mesh)
    steady = (time.perf_counter() - t0) / reps
    launch = sweep_ops.run_stats()["launches"][-1]
    n_models = F * sum(s["candidates"] for s in launch["per_shard"])
    print(f"steady {steady:.3f}s  ({n_models / steady:.1f} models/s)")
    costs = [s["predicted_cost"] for s in launch["per_shard"]]
    cmean = max(float(np.mean(costs)), 1e-9)
    wmean = max(float(np.mean([s["wall_s"] for s in launch["per_shard"]])), 1e-9)
    print(f"{'column':>6s} {'cands':>5s} {'rows_local':>10s} {'pred/mean':>9s} "
          f"{'meas/mean':>9s}")
    for i, s in enumerate(launch["per_shard"]):
        print(f"{i:6d} {s['candidates']:5d} {s['rows_local']:10d} "
              f"{s['predicted_cost'] / cmean:9.3f} {s['wall_s'] / wmean:9.3f}")
    for ax, c in launch["collectives"].items():
        print(f"collectives[{ax}]: count={c['count']} bytes={c['bytes']:,}"
              + "".join(f" {k}={v}" for k, v in sorted(c.items())
                        if k.endswith("_count")))
    pdb = launch["per_device_bytes"]
    print(f"per-device X+y bytes: rowsharded={pdb['X'] + pdb['y']:,} "
          f"replicated={pdb['X_replicated'] + pdb['y_replicated']:,} "
          f"(x{(pdb['X_replicated'] + pdb['y_replicated']) / max(pdb['X'] + pdb['y'], 1):.2f} saved)")
    _print_gbt_telemetry(sweep_ops)
    flops.disable()


from transmogrifai_tpu import obs  # noqa: E402

if args.data_shards > 0:
    profile_rowsharded(args.data_shards, max(args.shards, 1))
    extra = {"mode": "rowsharded"}
    try:
        from transmogrifai_tpu import costmodel
        from transmogrifai_tpu.ops import sweep as sweep_ops

        cm_eval = costmodel.eval_launches(sweep_ops.run_stats()["launches"])
        if cm_eval:
            extra["costmodel_eval"] = cm_eval
        extra["hedge"] = _print_hedge_telemetry(sweep_ops)
        extra["pack"] = _print_pack_telemetry(sweep_ops)
    except Exception:
        pass
    obs.write_record("profile_sweep", extra=extra)
    sys.exit(0)

if args.shards > 0:
    cm_eval, bub, roof = profile_shards(args.shards,
                                        use_costmodel=args.costmodel)
    extra = {"mode": "shards"}
    if cm_eval:
        extra["costmodel_eval"] = cm_eval
    if bub:
        extra["bubble_report"] = bub
    if roof:
        extra["roofline"] = roof
        extra["mfu_decomposition"] = roof["mfu_decomposition"]
    try:
        from transmogrifai_tpu.ops import sweep as sweep_ops

        extra["hedge"] = _print_hedge_telemetry(sweep_ops)
        extra["pack"] = _print_pack_telemetry(sweep_ops)
    except Exception:
        pass
    obs.write_record("profile_sweep", extra=extra)
    sys.exit(0)

rf = D.random_forest_grid()
by_depth = {}
for g in rf:
    by_depth.setdefault(g["max_depth"], []).append(g)

timed("LR x8", [(OpLogisticRegression(), D.logistic_regression_grid())])
for dep, gs in sorted(by_depth.items()):
    timed(f"RF depth={dep} x{len(gs)}", [(OpRandomForestClassifier(), gs)])
timed("RF all x18", [(OpRandomForestClassifier(), rf)])
timed("XGB x2", [(OpXGBoostClassifier(), D.xgboost_grid())])

from transmogrifai_tpu.ops import sweep as sweep_ops  # noqa: E402
_print_gbt_telemetry(sweep_ops)
obs.write_record("profile_sweep",
                 extra={"mode": "families",
                        "hedge": _print_hedge_telemetry(sweep_ops),
                        "pack": _print_pack_telemetry(sweep_ops)})
