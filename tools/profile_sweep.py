"""Per-family wall-clock profile of the Titanic default sweep (dev tool).

``--shards N`` instead partitions the default fused spec with the SAME cost
model the multi-chip sweep uses (parallel/spec_partition) and prints
predicted vs MEASURED per-shard cost — each shard run sequentially on one
device — so partitioner balance regressions are diagnosable without a pod.
"""
import argparse
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import init_backend, titanic_arrays

args = argparse.ArgumentParser(description=__doc__)
args.add_argument("--shards", type=int, default=0,
                  help="partition the default grid into N cost-balanced "
                       "shards and print predicted vs measured per-shard "
                       "cost (0 = legacy per-family profile)")
args = args.parse_args()

platform, fb = init_backend()
print("platform:", platform, fb)

from transmogrifai_tpu.evaluators.classification import OpBinaryClassificationEvaluator
from transmogrifai_tpu.impl.classification.logistic import OpLogisticRegression
from transmogrifai_tpu.impl.classification.trees import (
    OpRandomForestClassifier, OpXGBoostClassifier)
from transmogrifai_tpu.impl.selector import defaults as D
from transmogrifai_tpu.impl.tuning.validators import OpCrossValidation

X, y = titanic_arrays()
print("X", X.shape)

ev = OpBinaryClassificationEvaluator()


def timed(name, candidates, reps=3):
    cv = OpCrossValidation(ev, num_folds=3, seed=42)
    t0 = time.perf_counter()
    cv.validate(candidates, X, y)
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    for r in range(reps):
        cv = OpCrossValidation(ev, num_folds=3, seed=100 + r)
        cv.validate(candidates, X, y)
    dt = (time.perf_counter() - t0) / reps
    n = sum(len(g) for _, g in candidates)
    print(f"{name:30s} grids={n:3d} warm={warm:7.2f}s steady={dt:7.3f}s"
          f"  ({3*n/dt:8.1f} models/s)")
    return dt


def profile_shards(n_shards: int, reps: int = 3) -> None:
    """Predicted vs measured per-shard cost of the default 28-candidate grid."""
    import jax

    from transmogrifai_tpu.impl.sweep_fragments import build_sweep_plan
    from transmogrifai_tpu.ops.sweep import run_sweep
    from transmogrifai_tpu.parallel.spec_partition import (partition_spec,
                                                           predicted_balance)

    cands = [(OpLogisticRegression(max_iter=50), D.logistic_regression_grid()),
             (OpRandomForestClassifier(), D.random_forest_grid()),
             (OpXGBoostClassifier(), D.xgboost_grid())]
    F = 3
    cv = OpCrossValidation(ev, num_folds=F, seed=42)
    train_w, val_mask = cv.make_folds(len(y), None)
    plan = build_sweep_plan(cands, np.ascontiguousarray(X, np.float32), y,
                            train_w, ev)
    if plan is None:
        print("default grid did not build a fused plan; nothing to profile")
        return
    shards = partition_spec(plan.spec, plan.blob, n_shards, plan.n_rows,
                            plan.n_features, F)
    mx, mean = predicted_balance(shards)
    print(f"shards={len(shards)} predicted max/mean={mx / max(mean, 1e-9):.3f}")
    tw = np.asarray(train_w, np.float32)
    vw = np.asarray(val_mask, np.float32)
    walls = []
    for i, sh in enumerate(shards):
        # sequential, all on the default device: isolates per-shard COST
        # (the thing the partitioner predicts) from device contention
        out = run_sweep(sh.spec, plan.X, plan.xbs, plan.y, tw, vw, sh.blob)
        np.asarray(out)  # warm (compile)
        t0 = time.perf_counter()
        for _ in range(reps):
            np.asarray(run_sweep(sh.spec, plan.X, plan.xbs, plan.y, tw, vw,
                                 sh.blob))
        walls.append((time.perf_counter() - t0) / reps)
    wmean = float(np.mean(walls))
    print(f"{'shard':>5s} {'cands':>5s} {'predicted':>12s} {'pred/mean':>9s} "
          f"{'measured_s':>10s} {'meas/mean':>9s}")
    for i, (sh, w) in enumerate(zip(shards, walls)):
        print(f"{i:5d} {sh.n_candidates:5d} {sh.cost:12.3e} "
              f"{sh.cost / max(mean, 1e-9):9.3f} {w:10.4f} "
              f"{w / max(wmean, 1e-9):9.3f}")
    print(f"measured max/mean={max(walls) / max(wmean, 1e-9):.3f}")


if args.shards > 0:
    profile_shards(args.shards)
    sys.exit(0)

rf = D.random_forest_grid()
by_depth = {}
for g in rf:
    by_depth.setdefault(g["max_depth"], []).append(g)

timed("LR x8", [(OpLogisticRegression(), D.logistic_regression_grid())])
for dep, gs in sorted(by_depth.items()):
    timed(f"RF depth={dep} x{len(gs)}", [(OpRandomForestClassifier(), gs)])
timed("RF all x18", [(OpRandomForestClassifier(), rf)])
timed("XGB x2", [(OpXGBoostClassifier(), D.xgboost_grid())])
