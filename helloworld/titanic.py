"""OpTitanicSimple — binary classification on Titanic survival.

Reference parity: helloworld/src/main/scala/com/salesforce/hw/
OpTitanicSimple.scala:77-130 — the canonical example: typed features, the
``sibSp + parCh + 1`` DSL, transmogrify, sanity check, a
BinaryClassificationModelSelector CV sweep, and a train/score/evaluate app.

Run:
    python helloworld/titanic.py --run-type train --model-location /tmp/titanic_model
    python helloworld/titanic.py --run-type score --model-location /tmp/titanic_model \
        --write-location /tmp/titanic_scores
"""
import os
import sys

if __package__ in (None, ""):  # direct `python helloworld/x.py` execution
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import pandas as pd

import transmogrifai_tpu.types as T
from transmogrifai_tpu import (FeatureBuilder, OpAppWithRunner, OpWorkflow,
                               OpWorkflowRunner)
from transmogrifai_tpu.evaluators import OpBinaryClassificationEvaluator
from transmogrifai_tpu.impl.selector.factories import BinaryClassificationModelSelector
from transmogrifai_tpu.readers import DataReaders

TITANIC_CSV = "/root/reference/test-data/PassengerDataAllWithHeader.csv"


def titanic_data():
    if os.path.exists(TITANIC_CSV):
        return pd.read_csv(TITANIC_CSV)
    # synthetic fallback with the same schema
    rng = np.random.default_rng(0)
    n = 891
    sex = rng.choice(["male", "female"], n)
    pclass = rng.choice([1, 2, 3], n)
    age = rng.uniform(1, 80, n)
    y = ((sex == "female") | (rng.random(n) < 0.2)).astype(int)
    return pd.DataFrame({
        "PassengerId": np.arange(1, n + 1), "Survived": y, "Pclass": pclass,
        "Name": ["p"] * n, "Sex": sex, "Age": age,
        "SibSp": rng.integers(0, 4, n), "Parch": rng.integers(0, 3, n),
        "Ticket": ["t"] * n, "Fare": rng.uniform(5, 100, n),
        "Cabin": [None] * n, "Embarked": rng.choice(["S", "C", "Q"], n)})


def build_workflow():
    survived = FeatureBuilder("Survived", T.RealNN).extract(field="Survived").as_response()
    pclass = FeatureBuilder("Pclass", T.PickList).extract(field="Pclass").as_predictor()
    name = FeatureBuilder("Name", T.Text).extract(field="Name").as_predictor()
    sex = FeatureBuilder("Sex", T.PickList).extract(field="Sex").as_predictor()
    age = FeatureBuilder("Age", T.Real).extract(field="Age").as_predictor()
    sib_sp = FeatureBuilder("SibSp", T.Integral).extract(field="SibSp").as_predictor()
    par_ch = FeatureBuilder("Parch", T.Integral).extract(field="Parch").as_predictor()
    fare = FeatureBuilder("Fare", T.Real).extract(field="Fare").as_predictor()
    embarked = FeatureBuilder("Embarked", T.PickList).extract(field="Embarked").as_predictor()

    # the reference's derived feature (OpTitanicSimple.scala:93)
    family_size = (sib_sp + par_ch + 1).alias("family_size")
    features = family_size.vectorize(
        age, fare, label=survived).combine(
        sex.pivot(pclass, embarked, top_k=10, min_support=1),
        name.smart_vectorize(max_cardinality=10, num_hashes=64, min_support=1))
    checked = features.sanity_check(survived)

    pred = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3, seed=42).set_input(survived, checked).get_output()
    return OpWorkflow().set_result_features(pred), pred


class OpTitanicSimple(OpAppWithRunner):
    app_name = "OpTitanicSimple"

    def build_runner(self):
        wf, pred = build_workflow()
        reader = DataReaders.Simple.custom(titanic_data(), key="PassengerId")
        # prediction_col is left unset: a loaded model resolves its own
        # result-feature name (generated uids differ across processes)
        return OpWorkflowRunner(
            wf, train_reader=reader, scoring_reader=reader,
            evaluator=OpBinaryClassificationEvaluator(label_col="Survived"))


if __name__ == "__main__":
    OpTitanicSimple().main()
