"""OpBostonSimple — regression on Boston-housing-style data.

Reference parity: helloworld/src/main/scala/com/salesforce/hw/OpBostonSimple.scala
(RegressionModelSelector over numeric + categorical features).

Run:
    python helloworld/boston.py --run-type train --model-location /tmp/boston_model
"""
import os
import sys

if __package__ in (None, ""):  # direct `python helloworld/x.py` execution
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import pandas as pd

import transmogrifai_tpu.types as T
from transmogrifai_tpu import (FeatureBuilder, OpAppWithRunner, OpWorkflow,
                               OpWorkflowRunner)
from transmogrifai_tpu.evaluators import OpRegressionEvaluator
from transmogrifai_tpu.impl.selector.factories import RegressionModelSelector
from transmogrifai_tpu.readers import DataReaders


def boston_data(n: int = 506):
    """Synthetic housing data with the reference dataset's feature names."""
    rng = np.random.default_rng(13)
    crim = rng.exponential(3.0, n)
    rm = rng.normal(6.3, 0.7, n)          # rooms
    age = rng.uniform(2, 100, n)
    dis = rng.exponential(3.8, n)
    tax = rng.uniform(187, 711, n)
    lstat = rng.uniform(1.7, 38, n)
    chas = rng.choice([0, 1], n, p=[0.93, 0.07])
    medv = (9.1 * rm - 0.65 * lstat - 0.21 * crim - 0.02 * age
            + 2.7 * chas + rng.normal(0, 2.5, n) - 22.0)
    return pd.DataFrame({"id": np.arange(n), "crim": crim, "rm": rm, "age": age,
                         "dis": dis, "tax": tax, "lstat": lstat, "chas": chas,
                         "medv": medv})


def build_workflow():
    medv = FeatureBuilder("medv", T.RealNN).extract(field="medv").as_response()
    nums = [FeatureBuilder(n, T.Real).extract(field=n).as_predictor()
            for n in ("crim", "rm", "age", "dis", "tax", "lstat")]
    chas = FeatureBuilder("chas", T.PickList).extract(field="chas").as_predictor()
    features = nums[0].vectorize(*nums[1:]).combine(chas.pivot(min_support=1))
    pred = RegressionModelSelector.with_cross_validation(
        num_folds=3, seed=42).set_input(medv, features).get_output()
    return OpWorkflow().set_result_features(pred), pred


class OpBostonSimple(OpAppWithRunner):
    app_name = "OpBostonSimple"

    def build_runner(self):
        wf, pred = build_workflow()
        reader = DataReaders.Simple.custom(boston_data(), key="id")
        return OpWorkflowRunner(
            wf, train_reader=reader, scoring_reader=reader,
            evaluator=OpRegressionEvaluator(label_col="medv"))


if __name__ == "__main__":
    OpBostonSimple().main()
