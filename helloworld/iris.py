"""OpIrisSimple — multiclass classification on the Iris dataset.

Reference parity: helloworld/src/main/scala/com/salesforce/hw/OpIrisSimple.scala
(MultiClassificationModelSelector over the 4 numeric features + indexed label).

Run:
    python helloworld/iris.py --run-type train --model-location /tmp/iris_model
"""
import os
import sys

if __package__ in (None, ""):  # direct `python helloworld/x.py` execution
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import pandas as pd

import transmogrifai_tpu.types as T
from transmogrifai_tpu import (FeatureBuilder, OpAppWithRunner, OpWorkflow,
                               OpWorkflowRunner)
from transmogrifai_tpu.evaluators import OpMultiClassificationEvaluator
from transmogrifai_tpu.impl.selector.factories import MultiClassificationModelSelector
from transmogrifai_tpu.readers import DataReaders


def iris_data():
    """Deterministic synthetic iris: 3 Gaussian species clusters in 4-D."""
    rng = np.random.default_rng(7)
    centers = {"setosa": [5.0, 3.4, 1.5, 0.2],
               "versicolor": [5.9, 2.8, 4.3, 1.3],
               "virginica": [6.6, 3.0, 5.6, 2.0]}
    rows = []
    for label, c in centers.items():
        pts = rng.normal(c, [0.35, 0.3, 0.3, 0.15], size=(50, 4))
        for p in pts:
            rows.append({"sepal_length": p[0], "sepal_width": p[1],
                         "petal_length": p[2], "petal_width": p[3],
                         "species": label})
    df = pd.DataFrame(rows)
    df["id"] = np.arange(len(df))
    # label index (the reference indexes the species string)
    df["label"] = df["species"].map(
        {"setosa": 0.0, "versicolor": 1.0, "virginica": 2.0})
    return df


def build_workflow():
    label = FeatureBuilder("label", T.RealNN).extract(field="label").as_response()
    feats = [FeatureBuilder(n, T.Real).extract(field=n).as_predictor()
             for n in ("sepal_length", "sepal_width", "petal_length", "petal_width")]
    features = feats[0].vectorize(*feats[1:])
    pred = MultiClassificationModelSelector.with_cross_validation(
        num_folds=3, seed=42).set_input(label, features).get_output()
    return OpWorkflow().set_result_features(pred), pred


class OpIrisSimple(OpAppWithRunner):
    app_name = "OpIrisSimple"

    def build_runner(self):
        wf, pred = build_workflow()
        reader = DataReaders.Simple.custom(iris_data(), key="id")
        return OpWorkflowRunner(
            wf, train_reader=reader, scoring_reader=reader,
            evaluator=OpMultiClassificationEvaluator(label_col="label"))


if __name__ == "__main__":
    OpIrisSimple().main()
