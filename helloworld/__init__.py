"""Runnable example apps (reference: helloworld/src/main/scala/com/salesforce/hw).

Run as modules from the repo root (or after ``pip install -e .``):

    python -m helloworld.titanic --run-type train --model-location /tmp/titanic_model
    python -m helloworld.iris
    python -m helloworld.boston
    python -m helloworld.dataprep
"""
