"""Data-preparation examples: aggregate, conditional, and joined readers.

Reference parity: helloworld/src/main/scala/com/salesforce/hw/dataprep/
{JoinsAndAggregates,ConditionalAggregation}.scala — the two example apps
showing how OP's readers express complex event-data preparation in a few
lines:

- ``joins_and_aggregates``: two event tables ("Email Sends" / "Email
  Clicks") aggregate per user around a fixed cutoff (predictors before it,
  responses after), left-outer-join on the user key, and derive a CTR
  feature with the arithmetic DSL.
- ``conditional_aggregation``: web-visit events aggregate around a PER-KEY
  cutoff — the first visit to a target landing page; users who never hit
  the page are dropped.

Run:
    python -m helloworld.dataprep
"""
import os
import sys
from datetime import datetime, timezone

if __package__ in (None, ""):  # direct `python helloworld/dataprep.py` execution
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import transmogrifai_tpu.types as T
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.features.aggregators import SumNumeric
from transmogrifai_tpu.readers import DataReaders

REF_DATA = "/root/reference/helloworld/src/main/resources"
DAY_MS = 24 * 3600 * 1000


def _ts_ms(s: str) -> int:
    """'2017-09-01::10:00:00' -> epoch millis (the examples' format)."""
    return int(datetime.strptime(s, "%Y-%m-%d::%H:%M:%S")
               .replace(tzinfo=timezone.utc).timestamp() * 1000)


#: the JoinsAndAggregates cutoff: CutOffTime.DDMMYYYY("04092017")
CUTOFF_MS = _ts_ms("2017-09-04::00:00:00")


def joins_and_aggregates(clicks_csv: str = f"{REF_DATA}/EmailDataset/Clicks.csv",
                         sends_csv: str = f"{REF_DATA}/EmailDataset/Sends.csv"):
    """JoinsAndAggregates.scala:66 — returns the scored Dataset.

    Expected (reference :127-135): key 123 -> ctr 1.0, clicksYday 2.0,
    clicksTomorrow 1.0, sendsLastWeek 1.0; key 456 -> clicksTomorrow 1.0;
    key 789 -> sendsLastWeek 1.0.

    Null-vs-zero note: cells the reference table renders as 0.0 for keys
    456/789 are MISSING here.  The reference's own aggregator source makes
    an empty Sum the monoid zero ``None`` (SumReal, Numerics.scala:43-51),
    i.e. an empty Real — the table's 0.0 is Spark's join-fill rendering.
    This port keeps the typed-empty semantics (ctr of a missing operand is
    missing, per the reference's Real arithmetic truth table,
    RichNumericFeature.scala:73-81).
    """
    num_clicks_yday = (FeatureBuilder("numClicksYday", T.Real)
                       .extract(fn=lambda r: 1.0)
                       .aggregate(SumNumeric())
                       .window(1 * DAY_MS)
                       .as_predictor())
    num_sends_last_week = (FeatureBuilder("numSendsLastWeek", T.Real)
                           .extract(fn=lambda r: 1.0)
                           .aggregate(SumNumeric())
                           .window(7 * DAY_MS)
                           .as_predictor())
    num_clicks_tomorrow = (FeatureBuilder("numClicksTomorrow", T.Real)
                           .extract(fn=lambda r: 1.0)
                           .aggregate(SumNumeric())
                           .window(1 * DAY_MS)
                           .as_response())
    # .alias names the output column 'ctr' instead of the derived stage name
    ctr = (num_clicks_yday / (num_sends_last_week + 1)).alias("ctr")

    clicks_reader = DataReaders.Aggregate.csv_case(
        clicks_csv, key="userId", time_fn=lambda r: _ts_ms(r["timeStamp"]),
        cutoff_time_ms=CUTOFF_MS,
        schema=["clickId", "userId", "emailId", "timeStamp"])
    sends_reader = DataReaders.Aggregate.csv_case(
        sends_csv, key="userId", time_fn=lambda r: _ts_ms(r["timeStamp"]),
        cutoff_time_ms=CUTOFF_MS,
        schema=["sendId", "userId", "emailId", "timeStamp"])

    # the reference binds features to sources by record type
    # (FeatureBuilder.Real[Click] vs [Send]); fn-extractors carry no field
    # name, so the join declares the click-side features explicitly
    reader = sends_reader.left_outer_join(
        clicks_reader,
        right_features=["numClicksYday", "numClicksTomorrow"])

    model = (OpWorkflow()
             .set_reader(reader)
             .set_result_features(num_clicks_yday, num_clicks_tomorrow,
                                  num_sends_last_week, ctr)
             .train())
    return model.score()


def conditional_aggregation(visits_csv: str = f"{REF_DATA}/WebVisitsDataset/WebVisits.csv"):
    """ConditionalAggregation.scala:61 — returns the scored Dataset.

    Per-user cutoff = first visit to the SaveBig landing page; users who
    never hit it are dropped.  Expected (reference :105-113):
    xyz -> visitsPrior 3.0, purchasesNextDay 1.0; lmn -> 0.0, 1.0;
    abc -> 1.0, 0.0.
    """
    import math

    num_visits_week_prior = (FeatureBuilder("numVisitsWeekPrior", T.RealNN)
                             .extract(fn=lambda r: 1.0)
                             .aggregate(SumNumeric())
                             .window(7 * DAY_MS)
                             .as_predictor())

    def purchase(r):
        pid = r.get("productId")
        return 0.0 if pid is None or (isinstance(pid, float) and math.isnan(pid)) else 1.0

    num_purchases_next_day = (FeatureBuilder("numPurchasesNextDay", T.RealNN)
                              .extract(fn=purchase)
                              .aggregate(SumNumeric())
                              .window(1 * DAY_MS)
                              .as_response())

    visits_reader = DataReaders.Conditional.csv_case(
        visits_csv, key="userId",
        time_fn=lambda r: _ts_ms(r["timestamp"]),
        condition=lambda r: r["url"] == "http://www.amazon.com/SaveBig",
        response_window_ms=1 * DAY_MS,
        drop_if_no_condition=True,
        schema=["userId", "url", "productId", "price", "timestamp"])

    model = (OpWorkflow()
             .set_reader(visits_reader)
             .set_result_features(num_visits_week_prior, num_purchases_next_day)
             .train())
    return model.score()


def main():
    ds = joins_and_aggregates()
    print("JoinsAndAggregates:")
    names = ["numClicksYday", "numClicksTomorrow", "numSendsLastWeek", "ctr"]
    for i, k in enumerate(ds.key):
        row = {n: (ds[n].to_scalar(i).value if ds[n].mask[i] else None)
               for n in names}
        print(f"  key={k}: {row}")

    ds2 = conditional_aggregation()
    print("ConditionalAggregation:")
    for i, k in enumerate(ds2.key):
        row = {n: ds2[n].to_scalar(i).value
               for n in ("numVisitsWeekPrior", "numPurchasesNextDay")}
        print(f"  key={k}: {row}")


if __name__ == "__main__":
    main()
