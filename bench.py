"""Benchmark: the REAL ModelSelector default sweep (models trained / second).

The reference's hot path is the ModelSelector CV sweep — numFolds x models x
param-grids individual Spark fits throttled by an 8-thread JVM pool
(OpValidator.scala:299-357).  BASELINE.md sets the target: >=30x wall-clock
vs 32-core Spark-local on the full Titanic default sweep on TPU.

This benchmark times the framework's own code path end-to-end: Titanic
features through the framework's vectorizers, then
``BinaryClassificationModelSelector`` with the FULL REFERENCE DEFAULT grid —
LR (8 grids) + RandomForest (18: MaxDepth x MinInfoGain x
MinInstancesPerNode) + XGBoost (2) = 28 candidates x 3 folds = 84 model
fits — through ``ModelSelector.fit``'s ``find_best_estimator``, including
splitter holdout, DataBalancer preparation, the batched fold x grid XLA
sweeps, and validation metric evaluation
(BinaryClassificationModelSelector.scala:81-135, DefaultSelectorParams.scala).

Backend handling (round-2 VERDICT #1, round-4 VERDICT #1): the probe is
FRESH (bypasses the on-disk CPU-fallback cache) with an escalating
60/120/240 s schedule and logged PJRT diagnostics.  If the TPU never comes
up, the bench emits an explicit ``{"error": "tpu unavailable: ..."}`` JSON
instead of a misleading CPU measurement (TMOG_BENCH_ALLOW_CPU=1 overrides).

FLOPs / MFU (round-2 VERDICT #2): utils/flops.py records XLA
``cost_analysis()`` for every sweep kernel launch at its exact shapes; the
JSON reports ``flops_per_rep`` and ``mfu`` against the device's peak.
Honesty note on arithmetic intensity: the LR sweep is matmul-dominated (MXU)
and its MFU reads conventionally; the tree sweep's histogram building is
scatter/cumsum work on the VPU, so its contribution to "MFU" is utilization
of arithmetic throughput, not MXU duty cycle — on a tabular 891-row problem
the sweep is latency/bandwidth-bound by nature, which is exactly why
batching all 84 fits into a handful of launches wins.

Baseline: MEASURED, not invented (round-3 VERDICT #4).  ``baseline_proxy.py``
times the identical 28-grid x 3-fold sweep shape with scikit-learn on this
host's CPU and extrapolates perfect 8-thread scaling (the reference's JVM
pool width) — see BASELINE_MEASURED.json; ``vs_baseline`` divides by that
number.  Falls back to the old 4 models/s estimate only if the measured file
is absent.

Tunnel caveat: the axon device tunnel memoizes identical (executable, args)
executions, so every rep uses a DIFFERENT fold seed — new fold weights →
new device buffers → real executions (verified: identical-args reps return
in ~0 ms; varied-args reps pay real device time).

The path to 30x (round-5 accounting).  The whole 84-model sweep now runs as
ONE fused XLA launch (ops/sweep.py): measured 0.38 s steady on v5e = 220
models/s = 2.0x the measured baseline.  The remaining budget decomposes as
  - ~0.10 s wire: launch round trip (~25 ms) + fold-weight upload + metrics
    pull, each a tunnel RPC (tools/probe_latency.py);
  - ~0.18 s XGB boosting: 200 rounds x depth 10 = 2,000 SEQUENTIAL levels
    at ~90 us/level — the reference default NumRound=200 makes this chain
    irreducible in length; per-level time is small-tensor op overhead, not
    FLOPs;
  - ~0.10 s forests + FISTA + metrics.
On co-located hardware (PJRT local, ~100 us launches) the wire term
vanishes and the same program runs ~0.28 s -> ~300 models/s single-chip.
The remaining 10x is the model axis the design already ships: the sweep's
candidate axis shards over the mesh `model` dimension
(parallel/mesh.py, validators' legacy sharded path; the dryrun validates
8-way) — 8 chips x ~300 models/s covers the 30x target (3,286 models/s)
with the boosting chain split across chips, and the fused interpreter's
per-family batches are embarrassingly shardable the same way.  On this
one-chip tunnel the honest number stays what the JSON reports.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

TITANIC = "/root/reference/test-data/PassengerDataAllWithHeader.csv"


def baseline_models_per_sec():
    """Measured sklearn-proxy baseline (baseline_proxy.py), with provenance."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_MEASURED.json")
    try:
        with open(path) as f:
            m = json.load(f)
        return float(m["models_per_sec_8thread_linear"]), "measured-sklearn-8t"
    except Exception:
        return 4.0, "estimate"  # pre-round-4 fallback constant

#: peak dense arithmetic throughput per chip, FLOP/s — the canonical table
#: now lives in utils/backend.py next to the HBM-bandwidth peaks the
#: roofline ledger classifies against; re-exported here for compatibility
from transmogrifai_tpu.utils.backend import PEAK_FLOPS, device_peaks


def init_backend():
    """Initialize JAX robustly; returns (platform, fallback_reason|None).

    Round-4 lesson (VERDICT #1): when the configured platform is a TPU and
    the probe exhausts its budget, a CPU models/s number reads as a 50x
    regression, not as "tunnel was down".  So the bench REFUSES the silent
    fallback: it emits an explicit error JSON and exits.  Set
    TMOG_BENCH_ALLOW_CPU=1 to bench the CPU path deliberately (dev boxes
    where JAX_PLATFORMS=cpu don't hit this — no fallback reason is set)."""
    try:
        from transmogrifai_tpu.utils.backend import ensure_backend

        platform, fallback = ensure_backend(fresh=True)
    except Exception as e:  # pragma: no cover - nothing works
        print(json.dumps({"metric": "selector_sweep_models_per_sec",
                          "value": 0.0, "unit": "models/s", "vs_baseline": 0.0,
                          "error": f"no backend: {e}"}))
        sys.exit(0)
    if fallback and os.environ.get("TMOG_BENCH_ALLOW_CPU") != "1":
        print(json.dumps({"metric": "selector_sweep_models_per_sec",
                          "value": None, "unit": "models/s", "vs_baseline": None,
                          "error": f"tpu unavailable: {fallback}",
                          "platform": platform,
                          "note": "refusing CPU-fallback measurement; set "
                                  "TMOG_BENCH_ALLOW_CPU=1 to force"}))
        sys.exit(0)
    return platform, fallback


def titanic_arrays():
    """Titanic -> (X, y) via the framework's own vectorization pipeline."""
    import pandas as pd

    from transmogrifai_tpu.features.builder import from_dataframe
    from transmogrifai_tpu.impl.feature.vectorizers import (
        OneHotVectorizer, RealVectorizer, StandardScalerVectorizer, VectorsCombiner)
    from transmogrifai_tpu.readers.base import CustomReader

    if os.path.exists(TITANIC):
        df = pd.read_csv(TITANIC)
        df.columns = [c.strip() for c in df.columns]
    else:  # synthetic fallback, same schema/scale
        rng = np.random.default_rng(0)
        n = 891
        df = pd.DataFrame({
            "survived": rng.integers(0, 2, n),
            "age": np.where(rng.random(n) < 0.2, np.nan, rng.uniform(1, 80, n)),
            "fare": rng.uniform(5, 500, n),
            "sibSp": rng.integers(0, 5, n),
            "parCh": rng.integers(0, 5, n),
            "sex": rng.choice(["male", "female"], n),
            "embarked": rng.choice(["S", "C", "Q"], n),
            "pClass": rng.integers(1, 4, n).astype(str),
        })
    df.columns = [c[0].lower() + c[1:] for c in df.columns]
    label = "survived"
    num_cols = [c for c in ("age", "fare", "sibSp", "parch", "parCh") if c in df.columns]
    cat_cols = [c for c in ("sex", "embarked", "pclass", "pClass", "cabin")
                if c in df.columns]

    feats, resp = from_dataframe(df, response=label)
    by_name = {f.name: f for f in feats}
    by_name[label] = resp
    reader = CustomReader(df)
    ds = reader.generate_dataset(list(by_name.values()), {})

    num_vec = RealVectorizer().set_input(*[by_name[c] for c in num_cols])
    cat_vec = OneHotVectorizer().set_input(*[by_name[c] for c in cat_cols])
    nm = num_vec.fit(ds)
    cm = cat_vec.fit(ds)
    ds = ds.with_column(nm.get_output().name, nm.transform_dataset(ds))
    ds = ds.with_column(cm.get_output().name, cm.transform_dataset(ds))
    comb = VectorsCombiner().set_input(nm.get_output(), cm.get_output())
    vec = comb.transform_dataset(ds)
    ds = ds.with_column(comb.get_output().name, vec)
    scaler = StandardScalerVectorizer().set_input(comb.get_output())
    X = scaler.fit(ds).transform_dataset(ds).values
    ycol = ds[label]
    y = np.where(ycol.mask, ycol.values, 0.0).astype(np.float32)
    return np.asarray(X, np.float32), y


def transform_bench():
    """``bench.py --transform [rows] [--data-shards D]``: streamed transform wall.

    Times the workflow transform pipeline ONLY (fill + 2 vectorizers +
    combiner + scaler, fitted once on a head sample) over the same rows:
    the per-stage host path (what ran above TMOG_FUSE_MAX_ROWS before
    streaming) and the chunked streaming executor (workflow/stream.py).
    CPU-proxy friendly — run with JAX_PLATFORMS=cpu; the streamed number
    reports warm (includes the single compile) and steady separately.

    ``--data-shards D`` additionally times the mesh-sharded stream path
    (chunks round-robined over D data devices) against the single-device
    streamed wall and emits ``transform_stream_sharded_speedup``.  On a
    CPU host it forces ``xla_force_host_platform_device_count=D`` so the
    proxy actually has D devices; parity vs the host path is asserted for
    BOTH streamed runs (fill/concat bit-exact contract, scaler rtol 2e-6).
    """
    data_shards = 0
    argv = sys.argv[2:]
    if "--data-shards" in argv:
        i = argv.index("--data-shards")
        data_shards = int(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    if data_shards > 1 and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            flags = (flags +
                     f" --xla_force_host_platform_device_count={data_shards}")
        # one compute thread per proxy device: the single-device baseline
        # models ONE chip, the sharded run models D chips.  Without this the
        # shared XLA intra-op pool lets the "single device" use every core
        # and the proxy can never show device scaling.  TMOG_BENCH_PIN=0
        # opts out.  NOTE: on a host with < D cores the sharded number is
        # still core-bound — expect ~min(cores, D)/1 scaling, not Dx.
        if (os.environ.get("TMOG_BENCH_PIN", "1") != "0"
                and "intra_op_parallelism_threads" not in flags):
            flags += (" --xla_cpu_multi_thread_eigen=false"
                      " intra_op_parallelism_threads=1")
        os.environ["XLA_FLAGS"] = flags.strip()

    import transmogrifai_tpu.types as T
    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.columns import Dataset, NumericColumn
    from transmogrifai_tpu.impl.feature.transformers import FillMissingWithMean
    from transmogrifai_tpu.impl.feature.vectorizers import (
        RealVectorizer, StandardScalerVectorizer, VectorsCombiner)
    from transmogrifai_tpu.utils import flops
    from transmogrifai_tpu.workflow import stream

    platform, fallback = init_backend()
    rows = next((int(a) for a in argv if a.isdigit()), 1_000_000)
    n_feat = 8
    rng = np.random.default_rng(0)
    cols = {}
    for j in range(n_feat):
        v = rng.normal(size=rows).astype(np.float32)
        m = rng.random(rows) > 0.1
        cols[f"x{j}"] = NumericColumn(T.Real, np.where(m, v, 0.0), m)
    ds = Dataset(cols)
    head = Dataset({k: NumericColumn(c.ftype, c.values[:50_000], c.mask[:50_000])
                    for k, c in ds.columns.items()})

    xs = [FeatureBuilder(f"x{j}", T.Real).extract(field=f"x{j}").as_predictor()
          for j in range(n_feat)]
    fm = FillMissingWithMean().set_input(xs[0]).fit(head)
    m1 = RealVectorizer().set_input(*xs[:4]).fit(head)
    m2 = RealVectorizer(fill_with_mean=False, fill_value=-1.0).set_input(*xs[4:]).fit(head)
    comb = VectorsCombiner().set_input(m1.get_output(), m2.get_output())
    fit_ds = head
    for t in (fm, m1, m2, comb):
        fit_ds = fit_ds.with_column(t.get_output().name, t.transform_dataset(fit_ds))
    sm = StandardScalerVectorizer().set_input(comb.get_output()).fit(fit_ds)
    layers = [[fm, m1, m2], [comb], [sm]]
    final = sm.get_output().name

    # per-stage host path (the pre-streaming fallback above the fuse cliff)
    t0 = time.perf_counter()
    host = ds
    for t in (fm, m1, m2, comb, sm):
        host = host.with_column(t.get_output().name, t.transform_dataset(host))
    host_s = time.perf_counter() - t0

    # live={final}: the workflow's liveness pass materializes only columns
    # needed downstream — intermediates stay device-resident (the host path
    # has no such option; it materializes every stage output)
    if data_shards > 1:
        # pin the baseline pair to one device even when TMOG_MESH is set
        os.environ["TMOG_STREAM_ROUTE"] = "single"
        # unless the user pinned a chunking, pick one that gives every
        # device ~2 chunks; both streamed runs use it (same-work compare)
        if not os.environ.get("TMOG_TRANSFORM_CHUNK_ROWS"):
            c = max(4096, -(-rows // (2 * data_shards)))
            os.environ["TMOG_TRANSFORM_CHUNK_ROWS"] = str(-(-c // 256) * 256)
    flops.enable()
    stream.reset_stream_stats()
    t0 = time.perf_counter()
    out = stream.apply_streamed(ds, layers, live={final})
    warm_s = time.perf_counter() - t0
    assert out is not None, "streaming declined the bench pipeline"
    np.testing.assert_allclose(out[final].values, host[final].values,
                               rtol=2e-6, atol=1e-6)

    stream.reset_stream_stats()
    t0 = time.perf_counter()
    out = stream.apply_streamed(ds, layers, live={final})
    steady_s = time.perf_counter() - t0
    s = stream.stream_stats()
    streamed_flops = flops.totals().get("streamed") or {}
    flops.disable()

    sharded = None
    if data_shards > 1:
        os.environ.pop("TMOG_STREAM_ROUTE", None)
        os.environ["TMOG_STREAM_SHARDS"] = str(data_shards)
        stream.reset_stream_stats()
        t0 = time.perf_counter()
        out_sh = stream.apply_streamed(ds, layers, live={final})
        sharded_warm_s = time.perf_counter() - t0
        assert out_sh is not None, "sharded streaming declined the bench pipeline"
        np.testing.assert_allclose(out_sh[final].values, host[final].values,
                                   rtol=2e-6, atol=1e-6)
        stream.reset_stream_stats()
        t0 = time.perf_counter()
        out_sh = stream.apply_streamed(ds, layers, live={final})
        sharded_steady_s = time.perf_counter() - t0
        ss = stream.stream_stats()
        os.environ.pop("TMOG_STREAM_SHARDS", None)
        # honesty stamp: N virtual shards on < N physical cores time-slice
        # one core, so the "speedup" measures scheduler noise, not scaling —
        # the perf gate must not regress (or celebrate) such a number
        core_bound = (os.cpu_count() or 1) < data_shards
        sharded = {
            "metric": "transform_stream_sharded_speedup",
            "value": round(steady_s / sharded_steady_s, 2),
            "unit": "x vs single-device streamed path",
            "data_shards": data_shards,
            **({"core_bound": True} if core_bound else {}),
            "shards_used": ss["shards"],
            "stream_warm_s": round(sharded_warm_s, 3),
            "stream_steady_s": round(sharded_steady_s, 3),
            "transform_rows_per_sec": round(ss["transform_rows_per_sec"]),
            "chunks": ss["chunks"],
            "compiles_steady": ss["compiles"],
            "overlap_efficiency": round(ss["overlap_efficiency"], 3),
            "prep_s": round(ss["prep_s"], 3),
            "prep_blocked_s": round(ss["prep_blocked_s"], 3),
            "by_device": {k: v["chunks"] for k, v in ss["by_device"].items()},
        }

    report = {
        "metric": "transform_stream_speedup",
        "value": round(host_s / steady_s, 2),
        "unit": "x vs per-stage host path",
        "rows": rows,
        "features": n_feat,
        "vector_width": int(out[final].values.shape[1]),
        "host_wall_s": round(host_s, 3),
        "stream_warm_s": round(warm_s, 3),
        "stream_steady_s": round(steady_s, 3),
        "transform_rows_per_sec": round(s["transform_rows_per_sec"]),
        "chunks": s["chunks"],
        "chunk_rows": s["chunk_rows"],
        "pad_rows": s["pad_rows"],
        "buffers": stream.stream_buffers(),
        "stages_fused": s["stages_fused"],
        "compiles_steady": s["compiles"],
        "bytes_streamed_in": round(s["bytes_in"]),
        "bytes_streamed_out": round(s["bytes_out"]),
        "overlap_efficiency": round(s["overlap_efficiency"], 3),
        "streamed_flops_bucket": streamed_flops,
        "platform": platform,
        **({"backend_fallback": fallback} if fallback else {}),
        **({"sharded": sharded} if sharded else {}),
    }
    print(json.dumps(report))
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "STREAM_BENCH.json"), "w") as f:
        json.dump(report, f, indent=1)
    from transmogrifai_tpu import obs

    obs.write_record("bench", extra={"report": report})
    if sharded:
        obs.write_record("bench", extra={"report": sharded})


def serve_bench():
    """``bench.py --serve [replicas]``: replicated serving + AOT cache wall.

    Measures the fleet-serving acceptance pair on one host: (1) micro-batch
    throughput and p99 at 1 replica vs N replicas (same client load, same
    model), and (2) cold vs instant-warm deploy wall — the second deploy
    loads every per-bucket executable from the persistent AOT cache
    (TMOG_COMPILE_CACHE) instead of compiling.  CPU-proxy friendly.
    """
    import tempfile
    import threading

    import transmogrifai_tpu.types as T
    from transmogrifai_tpu import OpWorkflow
    from transmogrifai_tpu.impl.classification.logistic import (
        OpLogisticRegression)
    from transmogrifai_tpu.impl.feature.vectorizers import (
        OneHotVectorizer, RealVectorizer, VectorsCombiner)
    from transmogrifai_tpu.serve import (MicroBatcher, ModelRegistry,
                                         ServeMetrics)
    from transmogrifai_tpu.serve import compile_cache
    from transmogrifai_tpu.testkit import TestFeatureBuilder
    from transmogrifai_tpu.workflow.model import load_model

    platform, fallback = init_backend()
    import jax

    n_replicas = next((int(a) for a in sys.argv[2:] if a.isdigit()),
                      len(jax.devices()))
    n = 256
    ds, (x, cat, y) = TestFeatureBuilder.of(
        ("x", T.Real, list(np.linspace(-2, 2, n))),
        ("cat", T.PickList, ["a", "b", "c", "d"] * (n // 4)),
        ("y", T.RealNN, [float(i % 2) for i in range(n)]), response="y")
    feats = VectorsCombiner().set_input(
        RealVectorizer().set_input(x).get_output(),
        OneHotVectorizer(top_k=5, min_support=1).set_input(cat).get_output(),
    ).get_output()
    pred = OpLogisticRegression(reg_param=0.1).set_input(y, feats).get_output()
    model = OpWorkflow().set_input_dataset(ds).set_result_features(pred).train()

    tmp = tempfile.mkdtemp(prefix="tmog_serve_bench_")
    saved = os.path.join(tmp, "model")
    model.save(saved)
    os.environ["TMOG_COMPILE_CACHE"] = os.path.join(tmp, "aotx")
    clients, per_client = 64, 40

    def drive(replicas):
        compile_cache.reset_cache_stats()
        metrics = ServeMetrics()
        registry = ModelRegistry(max_batch=64, metrics=metrics,
                                 replicas=replicas)
        t0 = time.perf_counter()
        registry.deploy(load_model(saved))
        warm_s = time.perf_counter() - t0
        cache = compile_cache.cache_stats()
        batcher = MicroBatcher(registry, max_batch=64, max_wait_ms=2.0,
                               queue_size=8192, metrics=metrics).start()
        errors = []

        def client():
            try:
                for _ in range(per_client):
                    batcher.score({"x": 0.7, "cat": "b"}, timeout_s=120)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=client) for _ in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        dt = time.perf_counter() - t0
        batcher.stop()
        assert not errors, errors[:3]
        snap = metrics.snapshot()
        return {
            "replicas": registry.n_replicas,
            "warmup_s": round(warm_s, 3),
            "qps": round(clients * per_client / dt, 1),
            "p99_ms": snap["request_latency"]["p99_ms"],
            "resilience": {k: snap[k] for k in (
                "degraded_batches", "replica_failures", "replica_rebuilds")},
            "replica_slots_hit": sum(
                1 for s in snap["replicas"].values() if s["batches"]),
            "cache": {k: (round(cache[k], 3) if isinstance(cache[k], float)
                          else cache[k])
                      for k in ("hits", "misses", "compiles", "compile_s",
                                "load_s", "saves")},
        }

    fleet_cold = drive(n_replicas)  # empty cache: every (bucket, chip) compiles
    fleet = drive(n_replicas)       # warm: every executable deserializes
    single = drive(1)               # QPS baseline (cache state irrelevant)
    report = {
        "metric": "serve_replica_qps_speedup",
        "value": round(fleet["qps"] / single["qps"], 2),
        "unit": f"x qps at {fleet['replicas']} replicas vs 1",
        "warm_restart_speedup": round(
            fleet_cold["warmup_s"] / fleet["warmup_s"], 2),
        "single": single,
        "fleet": fleet,
        "fleet_cold": fleet_cold,
        "clients": clients,
        "requests": clients * per_client,
        "platform": platform,
        **({"backend_fallback": fallback} if fallback else {}),
    }
    print(json.dumps(report))
    from transmogrifai_tpu import obs

    obs.write_record("bench", extra={"report": report})

    # ---- multi-tenant fleet: N named tenants share the SAME chips ----------
    # aggregate QPS + worst per-tenant p99 at 1 vs 8/16/64 tenants, plus the
    # two lifecycle acceptance checks: an LRU-evicted tenant reactivates
    # through the compile cache's warm path with ZERO fresh XLA compiles, and
    # one tenant's hot-swap opens no capacity gap for its neighbours.
    from transmogrifai_tpu.serve import aot as serve_aot

    shared = load_model(saved)  # one model object: per-tenant warms memo-hit
    t_clients, t_per_client = 32, 8

    def drive_tenants(n_tenants):
        metrics = ServeMetrics()
        registry = ModelRegistry(max_batch=64, metrics=metrics,
                                 replicas=n_replicas)
        t0 = time.perf_counter()
        for i in range(n_tenants):
            registry.deploy(shared, tenant=f"t{i:02d}")
        warm_s = time.perf_counter() - t0
        batcher = MicroBatcher(registry, max_batch=64, max_wait_ms=2.0,
                               queue_size=8192, metrics=metrics).start()
        errors = []

        def client(idx):
            tenant = f"t{idx % n_tenants:02d}"
            try:
                for _ in range(t_per_client):
                    batcher.score({"x": 0.7, "cat": "b"}, timeout_s=120,
                                  tenant=tenant)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(t_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        dt = time.perf_counter() - t0
        assert not errors, errors[:3]

        # LRU eviction -> first-request reactivation: must be instant-warm
        registry.evict_tenant("t00")
        compile_cache.reset_cache_stats()
        serve_aot.reset_warm_stats()
        batcher.score({"x": 0.7, "cat": "b"}, timeout_s=120, tenant="t00")
        react_compiles = compile_cache.cache_stats()["compiles"]
        react_warms = serve_aot.warm_stats()

        # one tenant hot-swaps; a neighbour's traffic must never gap
        gap_errors: list = []
        swapped = {}
        if n_tenants >= 2:
            neighbour = f"t{min(2, n_tenants - 1):02d}"
            stop = threading.Event()

            def neighbour_traffic():
                while not stop.is_set():
                    try:
                        batcher.score({"x": 0.7, "cat": "b"}, timeout_s=120,
                                      tenant=neighbour)
                    except Exception as e:  # noqa: BLE001
                        gap_errors.append(e)

            th = threading.Thread(target=neighbour_traffic)
            th.start()
            before = metrics.snapshot()["tenants"][neighbour]["responses"]
            registry.deploy(load_model(saved), version="swap-v2",
                            tenant="t01")
            stop.set()
            th.join(60)
            after = metrics.snapshot()["tenants"][neighbour]["responses"]
            swapped = {"neighbour": neighbour,
                       "neighbour_responses_during_swap": after - before,
                       "capacity_gap_errors": len(gap_errors)}
            assert not gap_errors, gap_errors[:3]
        batcher.stop()
        snap = metrics.snapshot()
        p99s = [st["request_latency"]["p99_ms"]
                for st in snap["tenants"].values()
                if st["request_latency"]["count"]]
        return {
            "tenants": n_tenants,
            "replicas": registry.n_replicas,
            "warmup_s": round(warm_s, 3),
            "aggregate_qps": round(t_clients * t_per_client / dt, 1),
            "tenant_p99_ms_max": round(max(p99s), 3) if p99s else 0.0,
            "tenant_p99_ms_mean": (round(sum(p99s) / len(p99s), 3)
                                   if p99s else 0.0),
            "reactivation_compiles": react_compiles,
            "reactivation_warms": react_warms,
            "activations": snap["tenant_activations"],
            "reactivations": snap["tenant_reactivations"],
            "evictions": snap["tenant_evictions"],
            **swapped,
        }

    mt_single = drive_tenants(1)
    mt = {n: drive_tenants(n) for n in (8, 16, 64)}
    mt_report = {
        "metric": "serve_multi_tenant_qps",
        "value": round(mt[16]["aggregate_qps"] / mt_single["aggregate_qps"],
                       3),
        "unit": "x aggregate qps at 16 tenants vs 1 on the same chips",
        "single_tenant": mt_single,
        **{f"tenants_{n}": r for n, r in mt.items()},
        "reactivation_compiles": max(r["reactivation_compiles"]
                                     for r in mt.values()),
        "capacity_gap_errors": max(r.get("capacity_gap_errors", 0)
                                   for r in mt.values()),
        "platform": platform,
        **({"backend_fallback": fallback} if fallback else {}),
    }
    print(json.dumps(mt_report))
    obs.write_record("bench", extra={"report": mt_report})


def make_selector(seed: int = 42):
    from transmogrifai_tpu.impl.selector.factories import (
        BinaryClassificationModelSelector)

    return BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3, seed=seed)


def continual_bench():
    """``bench.py --continual [rows]``: warm-start retrain vs cold sweep wall.

    The continual-learning acceptance pair: a drift-triggered retrain prunes
    the selector grid to the incumbent winner's neighborhood
    (``ModelSelector.warm_start``), so its wall must be a fraction of the
    cold full-grid sweep that elected the champion.  Times both on the same
    synthetic two-era data the closed-loop harness uses and reports the
    speedup plus pruned-vs-full candidate counts.  CPU-proxy friendly.
    """
    from tools.continual_loop import _build, _workflow
    from transmogrifai_tpu.continual import incumbent_summary

    platform, fallback = init_backend()
    rows = next((int(a) for a in sys.argv[2:] if a.isdigit()), 256)

    ds_a, feats_a = _build(rows, 0.0)
    wf_cold = _workflow(ds_a, feats_a, 3)
    sel = next(s for s in wf_cold.stages
               if getattr(s, "is_model_selector", False))
    full = sum(len(g) for _, g in sel.models)
    t0 = time.perf_counter()
    champion = wf_cold.train()
    cold_s = time.perf_counter() - t0

    summary = incumbent_summary(champion)
    ds_b, feats_b = _build(rows, 3.0)
    wf_warm = _workflow(ds_b, feats_b, 3)
    sel_warm = next(s for s in wf_warm.stages
                    if getattr(s, "is_model_selector", False))
    sel_warm.warm_start(summary, explore=1)
    pruned, _ = sel_warm.validator.warm_start_counts
    t0 = time.perf_counter()
    wf_warm.train()
    warm_s = time.perf_counter() - t0

    report = {
        "metric": "continual_warm_retrain_speedup",
        "value": round(cold_s / warm_s, 2) if warm_s else None,
        "unit": f"x wall, {pruned}-grid warm retrain vs {full}-grid cold",
        "rows": rows,
        "cold_sweep_wall_s": round(cold_s, 3),
        "warm_retrain_wall_s": round(warm_s, 3),
        "full_candidates": full,
        "pruned_candidates": pruned,
        "incumbent": summary.best_model_type if summary else None,
        "platform": platform,
        **({"backend_fallback": fallback} if fallback else {}),
    }
    print(json.dumps(report))
    from transmogrifai_tpu import obs

    obs.write_record("bench", extra={"report": report})


def asha_bench():
    """``bench.py --asha [n_candidates]``: rung-scheduled search vs grid.

    The successive-halving acceptance pair: ASHA over a 500+ candidate
    superset of the stock binary space must (1) finish within a small
    multiple of the exhaustive 28-grid wall — that ratio is the perfgate
    metric (lower-better) — and (2) re-elect the exhaustive winner's
    family with a best metric inside a pinned tolerance (the parity
    metric, higher-better).  Both sides get one warmup pass so the timed
    walls compare steady executions, not compile queues.  CPU-proxy
    friendly.
    """
    from transmogrifai_tpu.impl.selector.defaults import asha_search_space
    from transmogrifai_tpu.impl.selector.factories import (
        BinaryClassificationModelSelector)

    platform, fallback = init_backend()
    n_cands = next((int(a) for a in sys.argv[2:] if a.isdigit()), 500)
    X, y = titanic_arrays()

    # exhaustive reference: the stock 28-grid (warm pass compiles)
    make_selector(seed=7).find_best_estimator(X, y)
    t0 = time.perf_counter()
    _, _, grid_summary = make_selector(seed=101).find_best_estimator(X, y)
    grid_s = time.perf_counter() - t0
    n_grid = len(grid_summary.results)

    def asha_selector(seed):
        return BinaryClassificationModelSelector.with_cross_validation(
            num_folds=3, seed=seed,
            models_and_parameters=asha_search_space(n_cands),
            search_strategy="asha")

    asha_selector(7).find_best_estimator(X, y)  # warm pass
    t0 = time.perf_counter()
    _, _, asha_summary = asha_selector(101).find_best_estimator(X, y)
    asha_s = time.perf_counter() - t0

    rungs = asha_summary.asha["rungs"]
    gb, ab = grid_summary.best, asha_summary.best
    winner_match = gb.model_name == ab.model_name
    metric_delta = abs(float(ab.metric_value) - float(gb.metric_value))
    evaluated = sum(r["candidates_in"] for r in rungs)

    wall_report = {
        "metric": "asha_500_vs_grid28_wall_ratio",
        "value": round(asha_s / max(grid_s, 1e-9), 3),
        "unit": f"x wall, {len(asha_summary.results)}-candidate ASHA vs "
                f"{n_grid}-grid exhaustive",
        "asha_wall_s": round(asha_s, 3),
        "grid_wall_s": round(grid_s, 3),
        "n_candidates": len(asha_summary.results),
        "n_grid": n_grid,
        "rungs_run": len(rungs),
        "reduction": asha_summary.asha["reduction"],
        "async": asha_summary.asha["async"],
        "candidate_evals": evaluated,
        "platform": platform,
        **({"backend_fallback": fallback} if fallback else {}),
    }
    parity_report = {
        "metric": "asha_best_metric_parity",
        "value": round(max(0.0, 1.0 - metric_delta), 4),
        "unit": "1 - |asha best - grid best| (same evaluator)",
        "winner_match": 1.0 if winner_match else 0.0,
        "grid_winner": gb.model_name,
        "grid_best_metric": round(float(gb.metric_value), 4),
        "asha_winner": ab.model_name,
        "asha_best_metric": round(float(ab.metric_value), 4),
        "metric_delta": round(metric_delta, 4),
        "platform": platform,
        **({"backend_fallback": fallback} if fallback else {}),
    }
    print(json.dumps(wall_report))
    print(json.dumps(parity_report))
    from transmogrifai_tpu import obs

    obs.write_record("bench", extra={"report": wall_report})
    obs.write_record("bench", extra={"report": parity_report})


def family_flops_breakdown(sel, X, y, train_w, val_mask):
    """Per-family single-launch XLA flops of the default sweep (LR/RF/XGB).

    Each family's fragment subset is lowered STANDALONE at the bench's exact
    fold shapes via ``flops.cost_of`` (no accumulation into the running
    totals), so the one ``sweep.run`` bucket decomposes into who actually
    burns the FLOPs.  Returns {} when the fused builder declines a family.
    """
    from transmogrifai_tpu.impl.sweep_fragments import build_sweep_plan
    from transmogrifai_tpu.ops.sweep import _run
    from transmogrifai_tpu.utils import flops

    fam_of = {"OpLogisticRegression": "LR", "OpLinearRegression": "LR",
              "OpRandomForestClassifier": "RF", "OpRandomForestRegressor": "RF",
              "OpDecisionTreeClassifier": "RF", "OpDecisionTreeRegressor": "RF",
              "OpGBTClassifier": "XGB", "OpXGBoostClassifier": "XGB",
              "OpGBTRegressor": "XGB", "OpXGBoostRegressor": "XGB"}
    tw = np.asarray(train_w, np.float32)
    vw = np.asarray(val_mask, np.float32)
    fams = {}
    for est, grids in sel.models:
        label = fam_of.get(type(est).__name__, "other")
        try:
            plan = build_sweep_plan([(est, grids)], X, y, tw,
                                    sel.validator.evaluator)
            if plan is None:
                continue
            cost = flops.cost_of(_run, plan.spec, plan.X, tuple(plan.xbs),
                                 plan.y, tw, vw, plan.blob)
        except Exception:
            continue
        if cost is None:
            continue
        fams[label] = fams.get(label, 0.0) + cost["flops"]
    return {k: round(v) for k, v in fams.items()}


def main():
    platform, fallback = init_backend()

    import jax

    from transmogrifai_tpu.utils import flops

    device_kind = jax.devices()[0].device_kind
    X, y = titanic_arrays()

    # reference default sweep: LR 8 + RF 18 + XGB 2 = 28 candidates
    sel = make_selector()
    n_grids = sum(len(g) for _, g in sel.models)
    n_models = sel.validator.num_folds * n_grids

    # warmup: compiles every kernel in the sweep (cached thereafter).  The
    # persistent compile cache (PR 8) is wired in FIRST so a warm-cache
    # bench run demonstrates the instant-warm number outside serve, and the
    # AOT compile telemetry splits the cold wall into compile vs dispatch.
    from transmogrifai_tpu.ops import sweep as sweep_ops
    sweep_ops._wire_compile_cache()
    sweep_ops.reset_run_stats()
    t_first = time.perf_counter()
    sel.find_best_estimator(X, y)
    warm = time.perf_counter() - t_first
    warmup_compile_s = float(sweep_ops.run_stats()["compile_s"])
    warmup_dispatch_s = max(warm - warmup_compile_s, 0.0)

    from transmogrifai_tpu.obs import ledger, timeline, trace

    flops.enable()
    flops.reset()
    ledger.enable()
    ledger.reset()
    reps = 3
    trace_was_on = trace.enabled()
    if not trace_was_on:
        trace.enable(path=None)  # in-memory: feed the bubble profiler
    t0 = time.perf_counter()
    with trace.span("bench.window", reps=reps):
        for r in range(reps):
            # new seed -> new folds -> new device buffers (defeats the
            # tunnel's (executable, args) memoization; also what a fresh
            # run would do)
            sel2 = make_selector(seed=100 + r)
            _, _, summary = sel2.find_best_estimator(X, y)
            assert summary.best.metric_value == summary.best.metric_value
    dt = (time.perf_counter() - t0) / reps
    try:
        bubble = timeline.bubble_report(window="bench.window",
                                        wall_s=dt * reps)
    except ValueError:
        bubble = None
    if not trace_was_on:
        trace.disable()
    acct = flops.totals()
    flops.disable()
    # roofline ledger: per-launch FLOPs/bytes vs the device peaks, factored
    # per family — the "which lever does each family need" report
    try:
        roof = ledger.ledger_report(window_wall_s=dt * reps,
                                    device_kind=device_kind,
                                    platform=platform, reps=reps)
    except ValueError:
        roof = None
    ledger.disable()
    ledger.reset()

    # sweep-launch telemetry (reset per validate: this is the LAST rep's),
    # so a multi-chip run shows its shard count + per-shard wall/compile —
    # the aggregate models/s above already spans all shards
    sweep_stats = sweep_ops.run_stats()

    models_per_sec = n_models / dt
    base, base_src = baseline_models_per_sec()
    out = {
        "metric": "selector_sweep_models_per_sec",
        "value": round(models_per_sec, 2),
        "unit": "models/s",
        "vs_baseline": round(models_per_sec / base, 2),
        "baseline_models_per_sec": base,
        "baseline_source": base_src,
        "platform": platform,
        "device_kind": device_kind,
        "sweep": f"{n_grids} grids x {sel.validator.num_folds} folds "
                 "(LR 8 + RF 18 + XGB 2 reference defaults)",
        "warmup_s": round(warm, 2),
        # cold-warmup decomposition: XLA compile seconds (AOT telemetry)
        # vs everything else (dispatch/upload/host) — the compile share is
        # what the persistent compile cache erases on a warm restart
        "warmup_compile_s": round(warmup_compile_s, 2),
        "warmup_dispatch_s": round(warmup_dispatch_s, 2),
        "steady_s": round(dt, 2),
        "sweep_shards": sweep_stats["sweep_shards"],
        "data_shards": sweep_stats["data_shards"],
        # candidate packing (TMOG_SWEEP_PACK): packed launches built in the
        # last rep, and sequential dispatches avoided vs one-launch-per-
        # candidate (always present so baselines can compare)
        "sweep_pack_count": int(sweep_stats.get("sweep_pack_count") or 0),
        "launches_avoided": int(sweep_stats.get("launches_avoided") or 0),
    }
    # sequential GBT launch-levels on the critical path: the full
    # dependency chain (steps x depth; K=4 round-collapse turns the
    # reference 200x10 = 2000 levels into 500), minus measured
    # cross-device overlap under TMOG_GBT_PIPELINE (gbt_chain_eff)
    if sweep_stats.get("gbt_chain_levels"):
        out["gbt_sequential_launches"] = (
            sweep_stats.get("gbt_sequential_launches")
            or sweep_stats["gbt_chain_levels"])
        out["gbt_chain_levels"] = sweep_stats["gbt_chain_levels"]
        out["gbt_chain_steps"] = sweep_stats["gbt_chain_steps"]
    bf = acct.get("bf16_hist") or {}
    if bf.get("levels"):
        out["bf16_hist_per_rep"] = {
            "levels": round(bf["levels"] / reps),
            "bytes_saved": round(bf["bytes_saved"] / reps)}
    hs = acct.get("hist_subtracted") or {}
    if hs.get("levels"):
        out["hist_subtracted_per_rep"] = {
            "levels": round(hs["levels"] / reps),
            "flops_avoided": round(hs["flops_avoided"] / reps)}
    per_shard = [s for l in sweep_stats["launches"] if l["shards"] > 1
                 for s in l["per_shard"]]
    if per_shard:
        out["sweep_per_shard"] = per_shard
    # straggler defense: duplicate dispatches fired + the losers' discarded
    # wall as a fraction of total sweep wall (perfgate lower-better policy —
    # the key is always present so baselines can compare it)
    hedges_fired = int(sweep_stats.get("hedges_fired") or 0)
    wasted_s = float(sweep_stats.get("hedge_wasted_s") or 0.0)
    total_wall = sum(s.get("wall_s", 0.0) for s in per_shard) or dt
    out["hedges_fired"] = hedges_fired
    out["hedge_wasted_s"] = round(wasted_s, 4)
    out["hedge_wasted_fraction"] = round(
        wasted_s / max(total_wall + wasted_s, 1e-9), 4)
    # predicted-vs-measured per-shard cost error (MAPE + makespan ratios):
    # every bench run appends its own eval row to the telemetry record, so
    # the learned cost model's eval set grows for free
    try:
        from transmogrifai_tpu import costmodel
        cm_eval = costmodel.eval_launches(sweep_stats["launches"])
        if cm_eval:
            out["costmodel_eval"] = cm_eval
    except Exception:
        pass
    # row-sharded launches: per-axis collective traffic + the memory story
    # (peak per-device X/y bytes vs what full replication would have held)
    coll_axes = {}
    for l in sweep_stats["launches"]:
        for ax, c in (l.get("collectives") or {}).items():
            agg = coll_axes.setdefault(ax, {"count": 0, "bytes": 0})
            agg["count"] += c["count"]
            agg["bytes"] += c["bytes"]
    if coll_axes:
        out["collective_bytes_by_axis"] = coll_axes
    pdb = next((l["per_device_bytes"] for l in reversed(sweep_stats["launches"])
                if l.get("rowsharded")), None)
    if pdb:
        out["per_device_bytes"] = pdb
        out["per_device_bytes_vs_replicated"] = round(
            (pdb["X"] + pdb["y"]) / max(pdb["X_replicated"] + pdb["y_replicated"], 1), 4)
    # per-rep collective accounting from the flops bucket (count + bytes per
    # axis, psum/all_gather split) — the communication half of MFU honesty
    if acct.get("collectives"):
        out["collectives_per_rep"] = {
            ax: {k: (round(v / reps) if isinstance(v, (int, float)) else v)
                 for k, v in c.items()}
            for ax, c in acct["collectives"].items()}
    if acct.get("by_device"):
        out["flops_by_device"] = {k: round(v["flops"] / reps)
                                  for k, v in acct["by_device"].items()}
    if acct["calls"]:
        flops_per_rep = acct["flops"] / reps
        out["flops_per_rep"] = round(flops_per_rep)
        out["flops_by_kernel"] = {k: round(v["flops"] / reps)
                                  for k, v in acct["by_fn"].items()}
        # decompose the single fused sweep.run bucket per model family by
        # lowering each family's fragment subset standalone at the same
        # shapes; residual (metrics glue, XLA fusion deltas) stays labeled
        tw, vm = sel.validator.make_folds(X.shape[0], y)
        fam = family_flops_breakdown(sel, X, y, tw, vm)
        if not fam and roof:
            # standalone re-lowering failed (BENCH_r05 fell back to the
            # single sweep.run bucket here): the ledger's per-family split
            # of the same cost_analysis totals is always available
            fam = {k: round(v["flops"] / reps)
                   for k, v in roof["by_family"].items()}
        if fam:
            out["flops_by_family"] = fam
            if "sweep.run" in out["flops_by_kernel"]:
                total = out["flops_by_kernel"].pop("sweep.run")
                for k, v in sorted(fam.items()):
                    out["flops_by_kernel"][f"sweep.run[{k}]"] = v
                rest = round(total - sum(fam.values()))
                if rest > 0:
                    out["flops_by_kernel"]["sweep.run[other]"] = rest
        out["bytes_per_rep"] = round(acct["bytes_accessed"] / reps)
        if roof:
            out["bytes_by_family"] = {
                k: round(v["bytes"] / reps)
                for k, v in roof["by_family"].items()}
        peak = device_peaks(device_kind)["peak_flops"]
        if platform != "cpu" and peak:
            out["mfu"] = round(flops_per_rep / dt / peak, 6)
            out["peak_flops"] = peak
        else:
            out["mfu"] = None  # no defensible CPU peak; see flops_per_rep
    else:
        out["flops_per_rep"] = None
        out["flops_note"] = "cost_analysis unavailable on this backend"
    if fallback:
        out["backend_fallback"] = fallback
    if bubble:
        # keep the headline report lean: bubble fractions inline, the full
        # per-lane report in the JSONL record only
        out["bubble_fraction"] = bubble["bubble_fraction"]
        print(timeline.format_report(bubble), file=sys.stderr)
    if roof:
        out["mfu_decomposition"] = roof["mfu_decomposition"]
        out["launch_bound_fraction"] = roof["launch_bound_fraction"]
        print(ledger.format_report(roof), file=sys.stderr)
    print(json.dumps(out))
    from transmogrifai_tpu import obs

    extra = {"report": out}
    if bubble:
        extra["bubble_report"] = bubble
    if roof:
        extra["roofline"] = roof
    obs.write_record("bench", extra=extra)


if __name__ == "__main__":
    if "--transform" in sys.argv:
        transform_bench()
    elif "--serve" in sys.argv:
        serve_bench()
    elif "--continual" in sys.argv:
        continual_bench()
    elif "--asha" in sys.argv:
        asha_bench()
    else:
        main()
