"""Benchmark: the REAL ModelSelector default sweep (models trained / second).

The reference's hot path is the ModelSelector CV sweep — numFolds x models x
param-grids individual Spark fits throttled by an 8-thread JVM pool
(OpValidator.scala:299-357; README's Titanic example evaluates 3 LR + 16 RF
models with 3-fold CV).  BASELINE.md sets the target: >=30x wall-clock vs
32-core Spark-local on a 48-model 3-fold Titanic-style sweep.

This benchmark times the framework's own code path end-to-end: Titanic
features through the framework's vectorizers, then
``BinaryClassificationModelSelector`` with the REFERENCE DEFAULT grid —
LR (8 grids) + RandomForest (6) + XGBoost (2) = 16 candidates x 3 folds =
48 model fits — through ``ModelSelector.fit``, including splitter holdout,
DataBalancer preparation, the batched fold x grid XLA sweeps, final refit
and train+holdout evaluation.

Backend handling: the experimental TPU platform can fail to initialize in
some environments; the bench falls back to CPU and RECORDS the reason
instead of crashing (round-1 failure mode).

Baseline constant: the reference publishes no wall-clock numbers
(BASELINE.md: "Reference wall-clock numbers must be measured locally") and
Spark is not installed in this image, so ``vs_baseline`` divides by a
DELIBERATELY GENEROUS estimate of Spark-local throughput: 8 concurrent JVM
threads (ValidatorParamDefaults.Parallelism=8) each completing a
Titanic-scale MLlib fit every 2s including job-scheduling overhead =>
4 models/s.  Treat the ratio as an order-of-magnitude indicator until a
measured Spark number replaces the constant.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_MODELS_PER_SEC = 4.0  # generous Spark-local 8-thread estimate (see above)
TITANIC = "/root/reference/test-data/PassengerDataAllWithHeader.csv"


def init_backend():
    """Initialize JAX robustly; returns (platform, fallback_reason|None).

    Round-1 failure mode: the experimental axon TPU plugin either raises
    ("Unable to initialize backend") or HANGS when the tunnel is absent.
    utils/backend.py probes in a subprocess with a timeout and falls back to
    CPU with a recorded reason — the bench always produces a JSON line.
    """
    try:
        from transmogrifai_tpu.utils.backend import ensure_backend

        return ensure_backend()
    except Exception as e:  # pragma: no cover - nothing works
        print(json.dumps({"metric": "selector_sweep_models_per_sec",
                          "value": 0.0, "unit": "models/s", "vs_baseline": 0.0,
                          "error": f"no backend: {e}"}))
        sys.exit(0)


def titanic_arrays():
    """Titanic -> (X, y) via the framework's own vectorization pipeline."""
    import pandas as pd

    from transmogrifai_tpu.features.builder import from_dataframe
    from transmogrifai_tpu.impl.feature.vectorizers import (
        OneHotVectorizer, RealVectorizer, StandardScalerVectorizer, VectorsCombiner)
    from transmogrifai_tpu.readers.base import CustomReader

    if os.path.exists(TITANIC):
        df = pd.read_csv(TITANIC)
        df.columns = [c.strip() for c in df.columns]
    else:  # synthetic fallback, same schema/scale
        rng = np.random.default_rng(0)
        n = 891
        df = pd.DataFrame({
            "survived": rng.integers(0, 2, n),
            "age": np.where(rng.random(n) < 0.2, np.nan, rng.uniform(1, 80, n)),
            "fare": rng.uniform(5, 500, n),
            "sibSp": rng.integers(0, 5, n),
            "parCh": rng.integers(0, 5, n),
            "sex": rng.choice(["male", "female"], n),
            "embarked": rng.choice(["S", "C", "Q"], n),
            "pClass": rng.integers(1, 4, n).astype(str),
        })
    df.columns = [c[0].lower() + c[1:] for c in df.columns]
    label = "survived"
    num_cols = [c for c in ("age", "fare", "sibSp", "parch", "parCh") if c in df.columns]
    cat_cols = [c for c in ("sex", "embarked", "pclass", "pClass", "cabin")
                if c in df.columns]

    feats, resp = from_dataframe(df, response=label)
    by_name = {f.name: f for f in feats}
    by_name[label] = resp
    reader = CustomReader(df)
    ds = reader.generate_dataset(list(by_name.values()), {})

    num_vec = RealVectorizer().set_input(*[by_name[c] for c in num_cols])
    cat_vec = OneHotVectorizer().set_input(*[by_name[c] for c in cat_cols])
    nm = num_vec.fit(ds)
    cm = cat_vec.fit(ds)
    ds = ds.with_column(nm.get_output().name, nm.transform_dataset(ds))
    ds = ds.with_column(cm.get_output().name, cm.transform_dataset(ds))
    comb = VectorsCombiner().set_input(nm.get_output(), cm.get_output())
    vec = comb.transform_dataset(ds)
    ds = ds.with_column(comb.get_output().name, vec)
    scaler = StandardScalerVectorizer().set_input(comb.get_output())
    X = scaler.fit(ds).transform_dataset(ds).values
    ycol = ds[label]
    y = np.where(ycol.mask, ycol.values, 0.0).astype(np.float32)
    return np.asarray(X, np.float32), y


def make_selector():
    from transmogrifai_tpu.impl.selector.factories import (
        BinaryClassificationModelSelector)

    return BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3, seed=42)


def main():
    platform, fallback = init_backend()

    X, y = titanic_arrays()

    # the sweep size of the REFERENCE default grid: LR 8 + RF 6 + XGB 2
    sel = make_selector()
    n_grids = sum(len(g) for _, g in sel.models)
    n_models = sel.validator.num_folds * n_grids

    # warmup: compiles every kernel in the sweep (cached thereafter)
    t_first = time.perf_counter()
    sel.find_best_estimator(X, y)
    warm = time.perf_counter() - t_first

    reps = 3
    t0 = time.perf_counter()
    for r in range(reps):
        sel2 = make_selector()
        sel2.validator.seed = 42 + r  # new folds; same compiled kernels
        _, _, summary = sel2.find_best_estimator(X, y)
        assert summary.best.metric_value == summary.best.metric_value  # finite
    dt = (time.perf_counter() - t0) / reps

    models_per_sec = n_models / dt
    out = {
        "metric": "selector_sweep_models_per_sec",
        "value": round(models_per_sec, 2),
        "unit": "models/s",
        "vs_baseline": round(models_per_sec / BASELINE_MODELS_PER_SEC, 2),
        "platform": platform,
        "sweep": f"{n_grids} grids x {sel.validator.num_folds} folds (LR+RF+XGB defaults)",
        "warmup_s": round(warm, 2),
        "steady_s": round(dt, 2),
    }
    if fallback:
        out["backend_fallback"] = fallback
    print(json.dumps(out))


if __name__ == "__main__":
    main()
