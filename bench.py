"""Benchmark: ModelSelector sweep throughput (models trained / second).

The reference's hot path is the ModelSelector CV sweep — numFolds x models x
param-grids individual Spark fits throttled by an 8-thread JVM pool
(OpValidator.scala:299-357; README's Titanic example evaluates 3 LR + 16 RF
models with 3-fold CV).  BASELINE.md sets the target: >=30x wall-clock vs
32-core Spark-local on a 48-model 3-fold Titanic-style sweep.

This benchmark times the TPU-native equivalent: the full fold x grid
logistic sweep as one compiled XLA program on real Titanic features
(Transmogrifier-style vectorization), reporting models-trained/sec.

Baseline constant: the reference publishes no wall-clock numbers
(BASELINE.md: "Reference wall-clock numbers must be measured locally") and
Spark is not installed in this image, so ``vs_baseline`` divides by a
DELIBERATELY GENEROUS estimate of Spark-local throughput: 8 concurrent JVM
threads (ValidatorParamDefaults.Parallelism=8) each completing a Titanic-scale
MLlib LR fit every 2s including job-scheduling overhead => 4 models/s.  Treat
the ratio as an order-of-magnitude indicator until a measured Spark number
replaces the constant.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_MODELS_PER_SEC = 4.0  # generous Spark-local 8-thread estimate (see above)
TITANIC = "/root/reference/test-data/PassengerDataAllWithHeader.csv"


def titanic_arrays():
    """Titanic -> (X, y) via the framework's own vectorization pipeline."""
    import pandas as pd

    from transmogrifai_tpu.features.builder import from_dataframe
    from transmogrifai_tpu.impl.feature.vectorizers import (
        OneHotVectorizer, RealVectorizer, StandardScalerVectorizer, VectorsCombiner)
    from transmogrifai_tpu.readers.base import CustomReader

    if os.path.exists(TITANIC):
        df = pd.read_csv(TITANIC)
        df.columns = [c.strip() for c in df.columns]
    else:  # synthetic fallback, same schema/scale
        rng = np.random.default_rng(0)
        n = 891
        df = pd.DataFrame({
            "survived": rng.integers(0, 2, n),
            "age": np.where(rng.random(n) < 0.2, np.nan, rng.uniform(1, 80, n)),
            "fare": rng.uniform(5, 500, n),
            "sibSp": rng.integers(0, 5, n),
            "parCh": rng.integers(0, 5, n),
            "sex": rng.choice(["male", "female"], n),
            "embarked": rng.choice(["S", "C", "Q"], n),
            "pClass": rng.integers(1, 4, n).astype(str),
        })
    df.columns = [c[0].lower() + c[1:] for c in df.columns]
    label = "survived"
    num_cols = [c for c in ("age", "fare", "sibSp", "parch", "parCh") if c in df.columns]
    cat_cols = [c for c in ("sex", "embarked", "pclass", "pClass", "cabin")
                if c in df.columns]

    feats, resp = from_dataframe(df, response=label)
    by_name = {f.name: f for f in feats}
    by_name[label] = resp
    reader = CustomReader(df)
    ds = reader.generate_dataset(list(by_name.values()), {})

    num_vec = RealVectorizer().set_input(*[by_name[c] for c in num_cols])
    cat_vec = OneHotVectorizer().set_input(*[by_name[c] for c in cat_cols])
    nm = num_vec.fit(ds)
    cm = cat_vec.fit(ds)
    ds = ds.with_column(nm.get_output().name, nm.transform_dataset(ds))
    ds = ds.with_column(cm.get_output().name, cm.transform_dataset(ds))
    comb = VectorsCombiner().set_input(nm.get_output(), cm.get_output())
    vec = comb.transform_dataset(ds)
    ds = ds.with_column(comb.get_output().name, vec)
    scaler = StandardScalerVectorizer().set_input(comb.get_output())
    X = scaler.fit(ds).transform_dataset(ds).values
    ycol = ds[label]
    y = np.where(ycol.mask, ycol.values, 0.0).astype(np.float32)
    return np.asarray(X, np.float32), y


def main():
    import jax

    from transmogrifai_tpu.parallel.sweep import (
        eval_logistic_grid_folds, fit_logistic_grid_folds, make_fold_weights)

    X, y = titanic_arrays()
    n_folds, grid_size = 3, 48  # the reference Titanic-class sweep (BASELINE.md)
    l2_grid = np.logspace(-4, 1, grid_size).astype(np.float32)
    train_w, val_w = make_fold_weights(len(y), n_folds, stratify_labels=y)

    import jax.numpy as jnp
    Xd = jnp.asarray(X, jnp.float32)
    yd = jnp.asarray(y, jnp.float32)
    tw = jnp.asarray(train_w)
    vw = jnp.asarray(val_w)
    l2 = jnp.asarray(l2_grid)

    # warmup / compile
    coef, intercept = fit_logistic_grid_folds(Xd, yd, tw, l2, max_iter=30)
    err = eval_logistic_grid_folds(Xd, yd, vw, coef, intercept)
    np.asarray(err)

    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        coef, intercept = fit_logistic_grid_folds(Xd, yd, tw, l2, max_iter=30)
        err = eval_logistic_grid_folds(Xd, yd, vw, coef, intercept)
        # device->host fetch: the selector needs fold metrics on host to pick
        # the winner, and block_until_ready alone does not guarantee
        # completion on the experimental axon platform.
        errs_host = np.asarray(err)
    dt = (time.perf_counter() - t0) / reps

    models_trained = n_folds * grid_size
    models_per_sec = models_trained / dt
    errs = errs_host.mean(axis=0)
    assert np.all(np.isfinite(errs)), "sweep produced non-finite CV errors"

    print(json.dumps({
        "metric": "selector_sweep_models_per_sec",
        "value": round(models_per_sec, 2),
        "unit": "models/s",
        "vs_baseline": round(models_per_sec / BASELINE_MODELS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
