/*
 * Scala facade of the transmogrifai_tpu bridge.
 *
 * Keeps the reference's user surface — OpWorkflow().train() / model.score()
 * / model.save() / OpWorkflow.loadModel() (reference
 * core/src/main/scala/com/salesforce/op/OpWorkflow.scala:61,347 and
 * OpWorkflowModel.scala:261,224) — while the execution substrate is the
 * Python/JAX runtime on TPU, reached over a socket protocol:
 *   frame = [1 byte kind 'J'|'A'][4-byte big-endian length][payload]
 *   'J' = UTF-8 JSON control, 'A' = Arrow IPC stream bytes.
 * See transmogrifai_tpu/bridge/protocol.py for the op catalogue.
 *
 * Dependencies: org.apache.arrow:arrow-vector + arrow-memory-netty (Arrow
 * IPC), and any JSON library (org.json used here for zero transitive deps).
 */
package com.salesforce.op.tpu

import java.io.{ByteArrayInputStream, ByteArrayOutputStream, DataInputStream, DataOutputStream}
import java.net.Socket
import java.nio.channels.Channels
import java.nio.charset.StandardCharsets.UTF_8

import org.apache.arrow.memory.RootAllocator
import org.apache.arrow.vector.VectorSchemaRoot
import org.apache.arrow.vector.ipc.{ArrowStreamReader, ArrowStreamWriter}
import org.json.JSONObject

/** One TCP session with the Python/JAX runtime. */
final class BridgeConnection(host: String, port: Int) extends AutoCloseable {
  private val socket = new Socket(host, port)
  private val in = new DataInputStream(socket.getInputStream)
  private val out = new DataOutputStream(socket.getOutputStream)
  private val allocator = new RootAllocator(Long.MaxValue)

  private def sendFrame(kind: Byte, payload: Array[Byte]): Unit = {
    out.writeByte(kind)
    out.writeInt(payload.length)
    out.write(payload)
    out.flush()
  }

  def sendJson(obj: JSONObject): Unit =
    sendFrame('J'.toByte, obj.toString.getBytes(UTF_8))

  def sendArrow(root: VectorSchemaRoot): Unit = {
    val buf = new ByteArrayOutputStream()
    val writer = new ArrowStreamWriter(root, null, Channels.newChannel(buf))
    writer.start(); writer.writeBatch(); writer.end(); writer.close()
    sendFrame('A'.toByte, buf.toByteArray)
  }

  private def readFrame(): (Byte, Array[Byte]) = {
    val kind = in.readByte()
    val len = in.readInt()
    val payload = new Array[Byte](len)
    in.readFully(payload)
    (kind, payload)
  }

  def recvJson(): JSONObject = {
    val (kind, payload) = readFrame()
    require(kind == 'J'.toByte, s"expected JSON frame, got $kind")
    val resp = new JSONObject(new String(payload, UTF_8))
    if (!resp.optBoolean("ok", false))
      throw new BridgeException(resp.optString("error", "bridge error"))
    resp
  }

  /** An op that returns data sends one Arrow frame, then its JSON status. */
  def recvArrowThenJson(): (VectorSchemaRoot, JSONObject) = {
    val (kind, payload) = readFrame()
    if (kind == 'J'.toByte) { // error instead of data
      val resp = new JSONObject(new String(payload, UTF_8))
      throw new BridgeException(resp.optString("error", "bridge error"))
    }
    val reader = new ArrowStreamReader(new ByteArrayInputStream(payload), allocator)
    reader.loadNextBatch()
    val root = reader.getVectorSchemaRoot
    (root, recvJson())
  }

  def call(op: String, fields: (String, Any)*): JSONObject = {
    val req = new JSONObject().put("op", op)
    fields.foreach { case (k, v) => req.put(k, v) }
    sendJson(req)
    recvJson()
  }

  override def close(): Unit = {
    try { sendJson(new JSONObject().put("op", "shutdown")); recvJson() }
    catch { case _: Exception => () }
    socket.close()
  }
}

final class BridgeException(msg: String) extends RuntimeException(msg)

object BridgeConnection {
  def apply(host: String = "127.0.0.1", port: Int = 7099): BridgeConnection =
    new BridgeConnection(host, port)
}

/**
 * Signature-compatible slice of the reference OpWorkflow
 * (OpWorkflow.scala:61): set input data + result features, then train().
 * Feature DAG definition crosses the bridge as a declarative JSON spec
 * (transmogrifai_tpu/bridge/spec.py) instead of closure-capturing
 * FeatureBuilders — the Python runtime reconstructs the typed DAG.
 */
final class OpWorkflow(conn: BridgeConnection, name: String = "wf") {
  private var dataName: Option[String] = None
  private var keyCol: Option[String] = None
  private var built = false

  /** Ship a dataset (Arrow) to the runtime under a name. */
  def setInputDataset(root: VectorSchemaRoot, key: String = null,
                      dataset: String = "train"): OpWorkflow = {
    conn.sendArrow(root)
    conn.call("put_data", "name" -> dataset)
    dataName = Some(dataset)
    keyCol = Option(key)
    this
  }

  /** Declarative workflow spec: features + stages + result names. */
  def setWorkflowSpec(spec: JSONObject): OpWorkflow = {
    conn.sendJson(new JSONObject().put("op", "build").put("name", name).put("spec", spec))
    conn.recvJson()
    built = true
    this
  }

  /** The reference entrypoint (OpWorkflow.train(), OpWorkflow.scala:347). */
  def train(modelName: String = "model"): OpWorkflowModel = {
    require(built, "setWorkflowSpec must be called before train()")
    val data = dataName.getOrElse(throw new IllegalStateException(
      "setInputDataset must be called before train()"))
    val fields = Seq("workflow" -> name, "data" -> data, "model" -> modelName) ++
      keyCol.map("key" -> _)
    conn.call("train", fields: _*)
    new OpWorkflowModel(conn, modelName)
  }
}

object OpWorkflow {
  /** OpWorkflow.loadModel analog (OpWorkflow.scala:483). */
  def loadModel(conn: BridgeConnection, path: String,
                modelName: String = "model"): OpWorkflowModel = {
    conn.call("load", "path" -> path, "model" -> modelName)
    new OpWorkflowModel(conn, modelName)
  }
}

/** Fitted-workflow handle (OpWorkflowModel.scala:60). */
final class OpWorkflowModel(conn: BridgeConnection, name: String) {
  /** Batch scoring (OpWorkflowModel.score, :261): Arrow in, Arrow out. */
  def score(root: VectorSchemaRoot, dataset: String = "score"): VectorSchemaRoot = {
    conn.sendArrow(root)
    conn.call("put_data", "name" -> dataset)
    conn.sendJson(new JSONObject().put("op", "score").put("model", name).put("data", dataset))
    conn.recvArrowThenJson()._1
  }

  /** scoreAndEvaluate analog (:298). */
  def evaluate(dataset: String, labelCol: String,
               evaluator: String = "binary"): JSONObject =
    conn.call("evaluate", "model" -> name, "data" -> dataset,
              "label" -> labelCol, "evaluator" -> evaluator)
      .getJSONObject("metrics")

  /** Model persistence on the runtime side (OpWorkflowModel.save, :224). */
  def save(path: String): Unit = conn.call("save", "model" -> name, "path" -> path)

  /** ModelSelector summary (summaryJson analog, :199). */
  def summary(): JSONObject = conn.call("summary", "model" -> name)
}
