"""BASELINE config #5 proof: synthetic 10M x 500 end-to-end AutoML at scale.

Pipeline (the real product path, not a side harness):
  500 raw typed features (460 Real + 40 PickList) -> CustomReader vectorized
  ingest -> Transmogrifier defaults -> SanityChecker with the row-sharded
  STREAMING stats path (two chunked passes over the mesh data axis; the
  O(p^2) feature-feature correlation as blocked centered-Gram MXU matmuls —
  SURVEY §2.7 axis 1 + §5.7) -> BinaryClassificationModelSelector with a
  64-candidate 5-fold CV grid (LR 44 FISTA + SVC 12 + MLP 8 — every
  candidate on the batched fold x grid XLA path; NaiveBayes excluded, see
  ``build``) -> train+holdout evaluation.

Scale choices, stated honestly:
- The ModelSelector trains on DataBalancer-prepared data capped at
  ``max_training_sample`` (reference SplitterParamDefaults 1E6; default here
  500k so the sweep's X fits one chip's HBM comfortably) — the reference
  applies exactly this cap.
- SanityChecker keeps the reference's 100k sample cap
  (``sample_upper_limit``, SanityChecker.scala:58-92) — identical
  semantics; the UNCAPPED one-pass streaming stats path is proven
  separately at multi-million-row scale
  (tests/test_sharded_stats.py + the round-5 3M-row device measurement).
- ``transmogrify`` runs without the label (no per-feature decision-tree
  bucketizers), matching the reference's plain ``.transmogrify()`` default.
- Workflow-level CV is opted out (``with_selector_cv``) to bound wall-clock:
  per-fold SanityChecker refits at 10M rows would 6x the stats passes; the
  equivalence of the two CV modes is tested at small scale
  (tests/test_workflow_cv.py).

Rows default to 10M; TMOG_SCALE_ROWS overrides (CI smoke uses ~100k).
Emits one JSON line with per-phase wall-clock + sweep models/s, and appends
the listener's per-stage metrics.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_ROWS = int(os.environ.get("TMOG_SCALE_ROWS", 10_000_000))
N_NUM = int(os.environ.get("TMOG_SCALE_NUM", 460))
N_CAT = int(os.environ.get("TMOG_SCALE_CAT", 40))
MAX_TRAIN = int(os.environ.get("TMOG_SCALE_MAX_TRAIN", 500_000))
FOLDS = 5


def synthesize(n: int, seed=7):
    """Synthetic COLUMNAR dataset (zero-copy into the reader's Dataset fast
    path — no 20 GB pandas shadow): informative numerics, correlated pairs,
    categorical signal, and a binary label — enough structure for the
    SanityChecker and selector to have something real to do.  ``seed`` may
    be a SeedSequence-style list — scale100m.py seeds per host so two hosts
    never synthesize the same rows."""
    import transmogrifai_tpu.types as T
    from transmogrifai_tpu.columns import Dataset, NumericColumn, ObjectColumn

    rng = np.random.default_rng(seed)
    cols = {}
    ones = np.ones(n, bool)
    signal = rng.normal(size=n).astype(np.float32)
    prev = None
    for j in range(N_NUM):
        noise = rng.normal(size=n).astype(np.float32)
        if j % 50 == 0:        # strongly informative
            v = signal * np.float32(0.8) + noise * np.float32(0.6)
        elif j % 50 == 1:      # near-duplicate of the previous (corr ~0.999)
            v = prev + noise * np.float32(0.02)
        elif j % 50 == 2:      # constant -> min-variance drop
            v = np.full(n, 3.14, np.float32)
        else:
            v = noise
        cols[f"num_{j}"] = NumericColumn(T.Real, v, ones)
        prev = v
    cats = np.array([f"c{k}" for k in range(8)], dtype=object)
    for j in range(N_CAT):
        idx = rng.integers(0, 8, n)
        if j % 10 == 0:  # label-associated category
            idx = np.where((signal > 0.5) & (rng.random(n) < 0.7), 0, idx)
        cols[f"cat_{j}"] = ObjectColumn(T.PickList, cats[idx])
    logits = signal * 1.5 + cols["num_0"].values * 0.5
    y = (logits + rng.logistic(size=n) > 0).astype(np.float32)
    cols["label"] = NumericColumn(T.RealNN, y, ones)
    return Dataset(cols)


def build(df):
    import transmogrifai_tpu.types as T
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.impl.feature.transmogrifier import transmogrify
    from transmogrifai_tpu.impl.selector.defaults import RandomParamBuilder
    from transmogrifai_tpu.impl.selector.factories import (
        BinaryClassificationModelSelector)
    from transmogrifai_tpu.impl.tuning.splitters import DataBalancer
    from transmogrifai_tpu.impl.classification.logistic import OpLogisticRegression
    from transmogrifai_tpu.impl.classification.svc import OpLinearSVC
    from transmogrifai_tpu.impl.classification.mlp import (
        OpMultilayerPerceptronClassifier)
    from transmogrifai_tpu.dsl import sanity_check  # noqa: F401 (registers DSL)

    label = FeatureBuilder("label", T.RealNN).extract(field="label").as_response()
    feats = [FeatureBuilder(f"num_{j}", T.Real).extract(field=f"num_{j}").as_predictor()
             for j in range(N_NUM)]
    feats += [FeatureBuilder(f"cat_{j}", T.PickList).extract(field=f"cat_{j}").as_predictor()
              for j in range(N_CAT)]

    vec = transmogrify(feats)
    checked = vec.sanity_check(label, sharded_stats=True)

    # 64 candidates, all on the batched fold x grid XLA path.  NaiveBayes is
    # excluded: vectorized numerics are signed and Spark NB (like ours)
    # rejects negative features — the reference leaves NB off by default too.
    lr_grids = (RandomParamBuilder(seed=11)
                .exponential("reg_param", 1e-4, 0.3)
                .uniform("elastic_net_param", 0.05, 0.95)
                .subset(44))
    svc_grids = [{"reg_param": r} for r in
                 (1e-4, 3e-4, 1e-3, 3e-3, 0.01, 0.02, 0.03, 0.06, 0.1, 0.15,
                  0.2, 0.3)]
    mlp_grids = [{"step_size": s, "seed": sd}
                 for s in (0.01, 0.03, 0.1, 0.2) for sd in (1, 2)]
    candidates = [
        (OpLogisticRegression(max_iter=200), lr_grids),
        (OpLinearSVC(max_iter=200), svc_grids),
        (OpMultilayerPerceptronClassifier(hidden_layers=(16,), max_iter=120),
         mlp_grids),
    ]
    n_cands = sum(len(g) for _, g in candidates)
    assert n_cands == 64, n_cands

    sel = BinaryClassificationModelSelector.with_cross_validation(
        splitter=DataBalancer(sample_fraction=0.1, reserve_test_fraction=0.1,
                              max_training_sample=MAX_TRAIN),
        num_folds=FOLDS, seed=42,
        models_and_parameters=candidates)
    pred = sel.set_input(label, checked).get_output()
    wf = (OpWorkflow().set_result_features(pred).set_input_dataset(df)
          .with_selector_cv())
    return wf, n_cands


def main():
    from transmogrifai_tpu.utils.backend import ensure_backend, start_keepalive

    platform, fallback = ensure_backend(fresh=True)
    # the tunneled TPU worker idles out during the long host-only vectorizer
    # phases at 10M rows; keep the session warm (utils/backend.start_keepalive)
    start_keepalive(60.0)
    from transmogrifai_tpu.utils.listener import OpListener

    def log(msg):
        print(f"[scale10m +{time.perf_counter() - t_start:.0f}s] {msg}",
              file=sys.stderr, flush=True)

    t_start = time.perf_counter()
    phases = {}
    log(f"platform={platform} rows={N_ROWS}")
    t0 = time.perf_counter()
    df = synthesize(N_ROWS)
    phases["generate_s"] = round(time.perf_counter() - t0, 2)
    log(f"synthesized {N_ROWS} rows x {N_NUM + N_CAT} features")

    t0 = time.perf_counter()
    wf, n_cands = build(df)
    listener = OpListener(app_name="scale10m", collect_stage_metrics=True)
    _orig = listener.time_stage

    def _loud_time_stage(stage, phase, n_rows=0):
        log(f"stage {getattr(stage, 'operation_name', stage)}.{phase} ({n_rows} rows)")
        return _orig(stage, phase, n_rows)

    listener.time_stage = _loud_time_stage
    with listener.install():
        model = wf.train()
    phases["train_s"] = round(time.perf_counter() - t0, 2)
    log("train done")

    # per-stage split from the listener (the per-phase numbers VERDICT #3 asks
    # for: vectorizer fits, SanityChecker streaming passes, selector sweep)
    stage_times = {}
    for m in listener.metrics.stage_metrics:
        key = f"{m.stage_name}.{m.phase}"
        stage_times[key] = round(stage_times.get(key, 0.0) + m.duration_ms / 1e3, 2)
    # read the winner straight off the fitted SelectedModel (no key spelunking)
    best_model = None
    for st in model.stages:
        s = getattr(st, "summary", None)
        if s is not None and getattr(s, "best_model_name", None):
            best_model = s.best_model_name
    sweep_s = next((v for k, v in stage_times.items()
                    if "odelSelector" in k and k.endswith(".fit")), None)
    # width of the sanity-checked vector the selector trained on (the
    # selector's second input; the result feature itself is the Prediction)
    vec_width = None
    try:
        sel_stage = next(st for st in model.stages
                         if getattr(st, "summary", None) is not None)
        vcol = model.train_data[sel_stage.inputs[1].name]
        vec_width = int(vcol.values.shape[1])
    except Exception:
        pass
    # honest metric name: only a run at the full 10M rows may claim the
    # scale10m metric; smoke runs are labelled by their actual row count
    metric = ("scale10m_train_wall_clock" if N_ROWS >= 10_000_000
              else f"scale_smoke_{N_ROWS}_rows_train_wall_clock")
    out = {
        "metric": metric,
        "value": phases["train_s"],
        "unit": "s",
        "rows": N_ROWS, "raw_features": N_NUM + N_CAT,
        "vector_width": vec_width,
        "platform": platform,
        "phases": phases,
        "stage_times_s": stage_times,
        "sweep_candidates": n_cands, "folds": FOLDS,
        "models_trained": n_cands * FOLDS,
        "sweep_s": sweep_s,
        "best_model": best_model,
    }
    # streaming-transform telemetry (workflow/stream.py): train() resets the
    # window, so these numbers are THIS run's — chunk counts + the <=1
    # steady-state compile prove the transform layers streamed rather than
    # falling back to per-stage host transforms above TMOG_FUSE_MAX_ROWS
    from transmogrifai_tpu.workflow import stream
    s = stream.stream_stats()
    if s["streams"]:
        out["stream"] = {
            "streams": s["streams"], "chunks": s["chunks"],
            "chunk_rows": s["chunk_rows"], "pad_rows": s["pad_rows"],
            "stages_fused": s["stages_fused"], "stages_host": s["stages_host"],
            "device_only": s["device_only"], "compiles": s["compiles"],
            "bytes_streamed_in": round(s["bytes_in"]),
            "bytes_streamed_out": round(s["bytes_out"]),
            "device_handoffs": s["device_handoffs"],
            "handoff_bytes": round(s["handoff_bytes"]),
            "transform_rows_per_sec": round(s["transform_rows_per_sec"]),
            "overlap_efficiency": round(s["overlap_efficiency"], 3),
            "fallbacks": s["fallbacks"],
            # mesh-sharded stream telemetry: shard count the router used,
            # host-prep walls (blocked share is what overlap_efficiency
            # reads from), winner-score stages routed through the sharded
            # head, and the per-device chunk/byte/wall split — an uneven
            # by_device map at scale means a straggling data shard
            "shards": s["shards"],
            "prep_s": round(s["prep_s"], 3),
            "prep_blocked_s": round(s["prep_blocked_s"], 3),
            "score_stages": s["score_stages"],
            "score_chunks": s["score_chunks"],
            "by_device": {
                k: {"chunks": v["chunks"], "rows": v["rows"],
                    "bytes_in": round(v["bytes_in"]),
                    "bytes_out": round(v["bytes_out"]),
                    "upload_s": round(v["upload_s"], 3),
                    "pull_wait_s": round(v["pull_wait_s"], 3)}
                for k, v in (s["by_device"] or {}).items()
            },
        }
    # sharded-vs-single score pass (the "modelSelector.transform is
    # single-chip" wall the mesh-sharded stream path attacks): when more
    # than one stream device is active, score the trained model over the
    # raw rows both ways and record the walls per stage — the single pass
    # pins TMOG_STREAM_ROUTE=single, the sharded pass uses the mesh
    try:
        from transmogrifai_tpu.parallel import mesh as pmesh
        if len(pmesh.stream_devices()) > 1:
            def _timed_score(tag):
                lst = OpListener(app_name=f"scale10m-score-{tag}",
                                 collect_stage_metrics=True)
                stream.reset_stream_stats()
                t0 = time.perf_counter()
                with lst.install():
                    model.score(df)
                wall = time.perf_counter() - t0
                per_stage = {}
                for m in lst.metrics.stage_metrics:
                    key = f"{m.stage_name}.{m.phase}"
                    per_stage[key] = round(per_stage.get(key, 0.0)
                                           + m.duration_ms / 1e3, 2)
                return wall, per_stage, stream.stream_stats()

            os.environ["TMOG_STREAM_ROUTE"] = "single"
            single_s, single_stages, _ = _timed_score("single")
            os.environ.pop("TMOG_STREAM_ROUTE", None)
            sharded_s, sharded_stages, ss = _timed_score("sharded")
            out["score_walls"] = {
                "single_s": round(single_s, 2),
                "sharded_s": round(sharded_s, 2),
                "speedup": round(single_s / max(sharded_s, 1e-9), 2),
                "shards": ss["shards"],
                "score_stages": ss["score_stages"],
                "score_chunks": ss["score_chunks"],
                "single_stage_s": single_stages,
                "sharded_stage_s": sharded_stages,
                "by_device": {k: v["chunks"]
                              for k, v in (ss["by_device"] or {}).items()},
            }
            log(f"score single {single_s:.2f}s sharded {sharded_s:.2f}s")
    except Exception as e:  # telemetry must never fail the scale run
        out["score_walls"] = {"error": str(e)}
    if fallback:
        out["backend_fallback"] = fallback
    print(json.dumps(out))
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "SCALE_r05.json"), "w") as f:
        json.dump(out, f, indent=1)
    from transmogrifai_tpu import obs

    obs.write_record("scale", extra={"report": out})


if __name__ == "__main__":
    main()
